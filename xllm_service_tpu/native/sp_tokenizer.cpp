// Native SentencePiece-Unigram tokenizer core.
//
// The reference ships a native sentencepiece family
// (xllm_service/tokenizer/sentencepiece_tokenizer.{h,cpp} wrapping the
// vendored sentencepiece C++ library). This is the rebuild's equivalent,
// self-contained: a hand-rolled ModelProto wire parser (the .model file
// is an ordinary protobuf) + Viterbi Unigram segmentation + byte
// fallback, behind a ctypes C ABI (tokenizer/native_sp.py wraps it).
//
// Scope: Unigram models with the standard normalizer options
// (add_dummy_prefix / escape_whitespaces / remove_extra_whitespaces).
// Precompiled charsmap normalization (NFKC) is NOT applied — the Python
// wrapper rejects models whose charsmap is non-empty unless the caller
// opts in, and the factory falls back to the transformers adapter.
//
// Build: g++ -O2 -shared -fPIC -std=c++17 sp_tokenizer.cpp -o libxllm_sp.so

#include <cstdint>
#include <cstring>
#include <string>
#include <unordered_map>
#include <vector>

namespace {

// ---------------------------------------------------------------- protobuf

struct Reader {
  const uint8_t* p;
  const uint8_t* end;
  bool ok = true;

  uint64_t varint() {
    uint64_t v = 0;
    int shift = 0;
    while (p < end && shift < 64) {
      uint8_t b = *p++;
      v |= uint64_t(b & 0x7f) << shift;
      if (!(b & 0x80)) return v;
      shift += 7;
    }
    ok = false;
    return 0;
  }

  // Returns (field_number, wire_type); field 0 on EOF.
  std::pair<uint32_t, uint32_t> tag() {
    if (p >= end) return {0, 0};
    uint64_t t = varint();
    return {uint32_t(t >> 3), uint32_t(t & 7)};
  }

  std::string_view bytes() {
    uint64_t n = varint();
    // Compare against the REMAINING size: `p + n` could wrap on a corrupt
    // near-2^64 varint length and slip past a pointer-sum check.
    if (!ok || n > uint64_t(end - p)) {
      ok = false;
      return {};
    }
    std::string_view out(reinterpret_cast<const char*>(p), size_t(n));
    p += n;
    return out;
  }

  float fixed32() {
    if (p + 4 > end) {
      ok = false;
      return 0.f;
    }
    float f;
    std::memcpy(&f, p, 4);
    p += 4;
    return f;
  }

  void skip(uint32_t wire) {
    switch (wire) {
      case 0: varint(); break;
      case 1: p += 8; break;
      case 2: bytes(); break;
      case 5: p += 4; break;
      default: ok = false;
    }
    if (p > end) ok = false;
  }
};

// SentencePiece piece types (sentencepiece.proto).
enum PieceType : int {
  kNormal = 1,
  kUnknown = 2,
  kControl = 3,
  kUserDefined = 4,
  kUnused = 5,
  kByte = 6,
};

constexpr const char kSpace[] = "\xe2\x96\x81";  // U+2581 LOWER ONE EIGHTH BLOCK

struct Model {
  std::vector<std::string> pieces;
  std::vector<float> scores;
  std::vector<int> types;
  std::unordered_map<std::string, int> piece_to_id;
  int unk_id = 0;
  int byte_ids[256];
  bool has_bytes = false;
  bool add_dummy_prefix = true;
  bool remove_extra_ws = true;
  bool escape_ws = true;
  bool has_charsmap = false;
  size_t max_piece_len = 1;
  float min_score = 0.f;
};

bool parse_normalizer(std::string_view buf, Model* m) {
  Reader r{reinterpret_cast<const uint8_t*>(buf.data()),
           reinterpret_cast<const uint8_t*>(buf.data()) + buf.size()};
  while (true) {
    auto [field, wire] = r.tag();
    if (!field) break;
    if (field == 2 && wire == 2) {
      m->has_charsmap = !r.bytes().empty();
    } else if (field == 3 && wire == 0) {
      m->add_dummy_prefix = r.varint() != 0;
    } else if (field == 4 && wire == 0) {
      m->remove_extra_ws = r.varint() != 0;
    } else if (field == 5 && wire == 0) {
      m->escape_ws = r.varint() != 0;
    } else {
      r.skip(wire);
    }
    if (!r.ok) return false;
  }
  return true;
}

bool parse_piece(std::string_view buf, Model* m) {
  Reader r{reinterpret_cast<const uint8_t*>(buf.data()),
           reinterpret_cast<const uint8_t*>(buf.data()) + buf.size()};
  std::string piece;
  float score = 0.f;
  int type = kNormal;
  while (true) {
    auto [field, wire] = r.tag();
    if (!field) break;
    if (field == 1 && wire == 2) {
      piece = std::string(r.bytes());
    } else if (field == 2 && wire == 5) {
      score = r.fixed32();
    } else if (field == 3 && wire == 0) {
      type = int(r.varint());
    } else {
      r.skip(wire);
    }
    if (!r.ok) return false;
  }
  int id = int(m->pieces.size());
  m->pieces.push_back(piece);
  m->scores.push_back(score);
  m->types.push_back(type);
  if (type == kUnknown) m->unk_id = id;
  if (type == kByte && piece.size() == 6 && piece[0] == '<' &&
      piece[1] == '0' && piece[2] == 'x' && piece[5] == '>') {
    auto hex = [](char c) -> int {
      if (c >= '0' && c <= '9') return c - '0';
      if (c >= 'A' && c <= 'F') return c - 'A' + 10;
      if (c >= 'a' && c <= 'f') return c - 'a' + 10;
      return -1;
    };
    int hi = hex(piece[3]), lo = hex(piece[4]);
    if (hi >= 0 && lo >= 0) m->byte_ids[hi * 16 + lo] = id;
  }
  // Matchable surface forms only (CONTROL/UNUSED never match from text).
  if (type == kNormal || type == kUserDefined || type == kUnknown) {
    m->piece_to_id.emplace(piece, id);
    m->max_piece_len = std::max(m->max_piece_len, piece.size());
  }
  return true;
}

Model* parse_model(const uint8_t* buf, int64_t len) {
  auto* m = new Model();
  for (int i = 0; i < 256; i++) m->byte_ids[i] = -1;
  Reader r{buf, buf + len};
  while (true) {
    auto [field, wire] = r.tag();
    if (!field) break;
    if (field == 1 && wire == 2) {  // repeated SentencePiece pieces
      if (!parse_piece(r.bytes(), m)) {
        delete m;
        return nullptr;
      }
    } else if (field == 3 && wire == 2) {  // NormalizerSpec
      if (!parse_normalizer(r.bytes(), m)) {
        delete m;
        return nullptr;
      }
    } else {
      r.skip(wire);
    }
    if (!r.ok || r.p > r.end) {
      delete m;
      return nullptr;
    }
  }
  if (m->pieces.empty()) {
    delete m;
    return nullptr;
  }
  m->has_bytes = true;
  for (int i = 0; i < 256 && m->has_bytes; i++)
    if (m->byte_ids[i] < 0) m->has_bytes = false;
  m->min_score = m->scores[0];
  for (float s : m->scores) m->min_score = std::min(m->min_score, s);
  return m;
}

// ------------------------------------------------------------- normalize

int utf8_len(uint8_t b) {
  if (b < 0x80) return 1;
  if ((b & 0xe0) == 0xc0) return 2;
  if ((b & 0xf0) == 0xe0) return 3;
  if ((b & 0xf8) == 0xf0) return 4;
  return 1;  // invalid byte: treat as single
}

std::string normalize(const Model& m, const char* text, size_t n) {
  std::string out;
  out.reserve(n + 8);
  bool prev_space = true;  // collapses leading spaces when remove_extra_ws
  if (m.add_dummy_prefix && n) out += m.escape_ws ? kSpace : " ";
  for (size_t i = 0; i < n; i++) {
    char c = text[i];
    if (c == ' ') {
      if (m.remove_extra_ws && prev_space) continue;
      out += m.escape_ws ? kSpace : " ";
      prev_space = true;
    } else {
      out += c;
      prev_space = false;
    }
  }
  if (m.remove_extra_ws) {
    // strip trailing escaped/plain spaces
    const std::string sp = m.escape_ws ? kSpace : " ";
    while (out.size() >= sp.size() &&
           out.compare(out.size() - sp.size(), sp.size(), sp) == 0)
      out.resize(out.size() - sp.size());
  }
  return out;
}

// --------------------------------------------------------------- viterbi

constexpr float kUnkPenalty = 10.0f;
constexpr float kNegInf = -1e30f;

int viterbi(const Model& m, const std::string& s, int32_t* out, int max_out) {
  const size_t n = s.size();
  if (!n) return 0;
  // char-boundary flags
  std::vector<uint8_t> boundary(n + 1, 0);
  boundary[0] = 1;
  for (size_t i = 0; i < n;) {
    i += utf8_len(uint8_t(s[i]));
    if (i <= n) boundary[i] = 1;
  }
  boundary[n] = 1;

  std::vector<float> best(n + 1, kNegInf);
  std::vector<int32_t> back_id(n + 1, -1);
  std::vector<int32_t> back_pos(n + 1, -1);
  best[0] = 0.f;
  const float unk_score = m.min_score - kUnkPenalty;

  std::string key;
  for (size_t i = 0; i < n; i++) {
    if (!boundary[i] || best[i] <= kNegInf / 2) continue;
    size_t maxj = std::min(n, i + m.max_piece_len);
    for (size_t j = i + 1; j <= maxj; j++) {
      if (!boundary[j]) continue;
      key.assign(s, i, j - i);
      auto it = m.piece_to_id.find(key);
      if (it != m.piece_to_id.end() && m.types[it->second] != kUnknown) {
        float cand = best[i] + m.scores[it->second];
        if (cand > best[j]) {
          best[j] = cand;
          back_id[j] = it->second;
          back_pos[j] = int32_t(i);
        }
      }
    }
    // Unknown single-char fallback (always available so segmentation
    // never dead-ends): one UNK per char, or byte pieces when the model
    // has the full byte alphabet.
    size_t j = i + utf8_len(uint8_t(s[i]));
    if (j > n) j = n;
    float cand = best[i] + unk_score;
    if (cand > best[j]) {
      best[j] = cand;
      back_id[j] = -2;  // sentinel: unk/byte expansion of s[i..j)
      back_pos[j] = int32_t(i);
    }
  }
  if (best[n] <= kNegInf / 2) return -1;

  // Walk back, then reverse.
  std::vector<int32_t> rev;
  rev.reserve(n / 2 + 4);
  for (size_t pos = n; pos > 0;) {
    int32_t id = back_id[pos];
    int32_t prev = back_pos[pos];
    if (id == -2) {
      if (m.has_bytes) {
        for (size_t b = pos; b > size_t(prev); b--)
          rev.push_back(m.byte_ids[uint8_t(s[b - 1])]);
      } else {
        rev.push_back(m.unk_id);
      }
    } else {
      rev.push_back(id);
    }
    pos = size_t(prev);
  }
  int count = int(rev.size());
  if (count > max_out) return -count;
  for (int i = 0; i < count; i++) out[i] = rev[count - 1 - i];
  return count;
}

}  // namespace

extern "C" {

void* sp_create(const uint8_t* buf, int64_t len) {
  return parse_model(buf, len);
}

void sp_destroy(void* h) { delete static_cast<Model*>(h); }

int sp_vocab_size(void* h) {
  return int(static_cast<Model*>(h)->pieces.size());
}

int sp_has_charsmap(void* h) {
  return static_cast<Model*>(h)->has_charsmap ? 1 : 0;
}

int sp_unk_id(void* h) { return static_cast<Model*>(h)->unk_id; }

// ids written to out; returns count, or -needed when max_out too small,
// or INT32_MIN on failure. `len` is the explicit byte length (embedded
// NUL bytes tokenize via byte fallback, same as real sentencepiece).
int sp_encode(void* h, const char* text, int64_t len, int32_t* out,
              int max_out) {
  auto& m = *static_cast<Model*>(h);
  std::string norm = normalize(m, text, size_t(len));
  int r = viterbi(m, norm, out, max_out);
  return r == -1 ? INT32_MIN : r;
}

// Decoded text written to out (NUL-terminated); returns byte length, or
// -needed when max_out too small.
int sp_decode(void* h, const int32_t* ids, int n, char* out, int max_out) {
  auto& m = *static_cast<Model*>(h);
  std::string s;
  for (int i = 0; i < n; i++) {
    int id = ids[i];
    if (id < 0 || size_t(id) >= m.pieces.size()) continue;
    if (m.types[id] == kControl) continue;
    if (m.types[id] == kByte) {
      const std::string& p = m.pieces[id];
      if (p.size() == 6) {
        auto hex = [](char c) -> int {
          if (c >= '0' && c <= '9') return c - '0';
          if (c >= 'A' && c <= 'F') return c - 'A' + 10;
          if (c >= 'a' && c <= 'f') return c - 'a' + 10;
          return 0;
        };
        s += char(hex(p[3]) * 16 + hex(p[4]));
      }
      continue;
    }
    s += m.pieces[id];
  }
  // un-escape ▁ -> space
  std::string t;
  t.reserve(s.size());
  for (size_t i = 0; i < s.size();) {
    if (i + 3 <= s.size() && s.compare(i, 3, kSpace) == 0) {
      t += ' ';
      i += 3;
    } else {
      t += s[i++];
    }
  }
  // drop the dummy-prefix space
  size_t start = (m.add_dummy_prefix && !t.empty() && t[0] == ' ') ? 1 : 0;
  int len = int(t.size() - start);
  if (len + 1 > max_out) return -(len + 1);
  std::memcpy(out, t.data() + start, size_t(len));
  out[len] = 0;
  return len;
}

int sp_piece_to_id(void* h, const char* piece) {
  auto& m = *static_cast<Model*>(h);
  // CONTROL pieces (bos/eos) are looked up here too — scan all.
  auto it = m.piece_to_id.find(piece);
  if (it != m.piece_to_id.end()) return it->second;
  for (size_t i = 0; i < m.pieces.size(); i++)
    if (m.pieces[i] == piece) return int(i);
  return -1;
}

int sp_id_to_piece(void* h, int id, char* out, int max_out) {
  auto& m = *static_cast<Model*>(h);
  if (id < 0 || size_t(id) >= m.pieces.size()) return -1;
  const std::string& p = m.pieces[id];
  if (int(p.size()) + 1 > max_out) return -(int(p.size()) + 1);
  std::memcpy(out, p.data(), p.size());
  out[p.size()] = 0;
  return int(p.size());
}

int sp_piece_type(void* h, int id) {
  auto& m = *static_cast<Model*>(h);
  if (id < 0 || size_t(id) >= m.pieces.size()) return -1;
  return m.types[id];
}

}  // extern "C"
