// Native paged-KV block store: the engine's hot bookkeeping path.
//
// C++ core for runtime/block_manager.py semantics (the reference's
// allocator/scheduler tier is likewise native): free-list + refcounted
// blocks, content-addressed committed-block index keyed by 16-byte chained
// murmur3 hashes (common/hashing.py contract), LRU eviction of unreferenced
// committed blocks, and the stored/removed/offloaded event deltas the
// heartbeat drains. Thread-safe (the heartbeat thread drains events while
// the engine thread mutates).
//
// Exposed as a C ABI consumed via ctypes (runtime/native_blocks.py); the
// Python BlockManager remains as fallback and as the parity oracle in
// tests/test_native_blocks.py.

#include <cstdint>
#include <cstring>
#include <list>
#include <map>
#include <mutex>
#include <set>
#include <string>
#include <unordered_map>
#include <vector>

namespace {

constexpr int kHashLen = 16;

struct BlockInfo {
  int ref = 0;
  bool has_hash = false;
  std::string hash;  // 16 bytes when has_hash
  bool in_evictable = false;
  std::list<int>::iterator lru_it;
};

struct Store {
  std::mutex mu;
  int num_blocks = 0;
  int block_size = 0;
  std::vector<BlockInfo> blocks;
  std::vector<int> free_list;                       // LIFO like the Python pop()
  std::unordered_map<std::string, int> hash_index;  // committed hash -> block
  std::list<int> evictable;                         // front = LRU victim

  // Heartbeat event deltas (guarded by mu, like BlockManager._ev_mu).
  std::set<std::string> stored;
  std::set<std::string> removed;
  std::map<std::string, int> offloaded;  // hash -> tier (0=dram, 1=ssd)

  int free_count_locked() const {
    return static_cast<int>(free_list.size() + evictable.size());
  }
};

std::string key_of(const char* h) { return std::string(h, kHashLen); }

void detach_evictable(Store* s, int id) {
  BlockInfo& b = s->blocks[id];
  if (b.in_evictable) {
    s->evictable.erase(b.lru_it);
    b.in_evictable = false;
  }
}

}  // namespace

extern "C" {

void* xbs_new(int num_blocks, int block_size) {
  if (num_blocks < 2) return nullptr;
  auto* s = new Store();
  s->num_blocks = num_blocks;
  s->block_size = block_size;
  s->blocks.resize(num_blocks);
  s->free_list.reserve(num_blocks - 1);
  // Block 0 is the reserved garbage slot — never allocated.
  for (int i = 1; i < num_blocks; ++i) s->free_list.push_back(i);
  return s;
}

void xbs_free_store(void* p) { delete static_cast<Store*>(p); }

int xbs_num_free(void* p) {
  auto* s = static_cast<Store*>(p);
  std::lock_guard<std::mutex> g(s->mu);
  return s->free_count_locked();
}

// Blocks currently holding live references (ref > 0). Diagnostic /
// invariant hook: after the engine drains, this must be 0 — anything else
// is a leaked reference (the stress harness asserts on it).
int xbs_num_referenced(void* p) {
  auto* s = static_cast<Store*>(p);
  std::lock_guard<std::mutex> g(s->mu);
  int n = 0;
  for (int i = 1; i < s->num_blocks; ++i)
    if (s->blocks[i].ref > 0) ++n;
  return n;
}

// Allocate n blocks (ref=1 each). Committed LRU victims are UN-indexed and
// reported via out_evicted_{ids,hashes} so the caller can offer their
// content to a colder tier, then record the matching event. Returns 0 on
// success, -1 if capacity is insufficient (nothing changes).
int xbs_allocate(void* p, int n, int32_t* out_ids, int32_t* out_evicted_ids,
                 char* out_evicted_hashes, int* n_evicted) {
  auto* s = static_cast<Store*>(p);
  std::lock_guard<std::mutex> g(s->mu);
  *n_evicted = 0;
  if (n > s->free_count_locked()) return -1;
  int got = 0;
  while (got < n && !s->free_list.empty()) {
    int id = s->free_list.back();
    s->free_list.pop_back();
    s->blocks[id].ref = 1;
    out_ids[got++] = id;
  }
  while (got < n) {
    int victim = s->evictable.front();
    s->evictable.pop_front();
    BlockInfo& b = s->blocks[victim];
    b.in_evictable = false;
    if (b.has_hash) {
      s->hash_index.erase(b.hash);
      out_evicted_ids[*n_evicted] = victim;
      std::memcpy(out_evicted_hashes + *n_evicted * kHashLen, b.hash.data(),
                  kHashLen);
      ++(*n_evicted);
      b.has_hash = false;
      b.hash.clear();
    }
    b.ref = 1;
    out_ids[got++] = victim;
  }
  return 0;
}

void xbs_acquire(void* p, int id) {
  auto* s = static_cast<Store*>(p);
  std::lock_guard<std::mutex> g(s->mu);
  if (id < 1 || id >= s->num_blocks) return;  // bounds: no UB on bad ids
  BlockInfo& b = s->blocks[id];
  if (b.ref == 0) detach_evictable(s, id);
  b.ref += 1;
}

// Releases every VALID id; returns 0 when all were valid live references,
// -1 if any id was out of range or double-freed (the rest still release —
// a partial abort would leak the tail of the list).
int xbs_release(void* p, const int32_t* ids, int n) {
  auto* s = static_cast<Store*>(p);
  std::lock_guard<std::mutex> g(s->mu);
  int rc = 0;
  for (int i = 0; i < n; ++i) {
    if (ids[i] < 1 || ids[i] >= s->num_blocks) {
      rc = -1;
      continue;
    }
    BlockInfo& b = s->blocks[ids[i]];
    if (b.ref <= 0) {
      rc = -1;
      continue;
    }
    b.ref -= 1;
    if (b.ref == 0) {
      if (b.has_hash) {
        s->evictable.push_back(ids[i]);
        b.lru_it = std::prev(s->evictable.end());
        b.in_evictable = true;
      } else {
        s->free_list.push_back(ids[i]);
      }
    }
  }
  return rc;
}

// Returns 1 if the block was committed under the hash, 0 if the hash is
// already indexed elsewhere or the block already carries a hash.
int xbs_commit(void* p, int id, const char* hash) {
  auto* s = static_cast<Store*>(p);
  std::lock_guard<std::mutex> g(s->mu);
  if (id < 1 || id >= s->num_blocks) return 0;
  std::string k = key_of(hash);
  if (s->hash_index.count(k)) return 0;
  BlockInfo& b = s->blocks[id];
  if (b.has_hash) return 0;
  b.has_hash = true;
  b.hash = k;
  s->hash_index[k] = id;
  s->stored.insert(k);
  s->removed.erase(k);
  s->offloaded.erase(k);
  return 1;
}

int xbs_lookup(void* p, const char* hash) {
  auto* s = static_cast<Store*>(p);
  std::lock_guard<std::mutex> g(s->mu);
  auto it = s->hash_index.find(key_of(hash));
  return it == s->hash_index.end() ? -1 : it->second;
}

// Longest-prefix walk over n chained hashes; matched blocks are acquired
// (ref+1, detached from the LRU). Returns the match count.
int xbs_match_prefix(void* p, const char* hashes, int n, int32_t* out_ids) {
  auto* s = static_cast<Store*>(p);
  std::lock_guard<std::mutex> g(s->mu);
  int matched = 0;
  for (int i = 0; i < n; ++i) {
    auto it = s->hash_index.find(key_of(hashes + i * kHashLen));
    if (it == s->hash_index.end()) break;
    out_ids[matched++] = it->second;
  }
  for (int i = 0; i < matched; ++i) {
    BlockInfo& b = s->blocks[out_ids[i]];
    if (b.ref == 0) detach_evictable(s, out_ids[i]);
    b.ref += 1;
  }
  return matched;
}

// Event recording — guards mirror block_manager.py exactly.
void xbs_record_removed_unless_hot(void* p, const char* hash) {
  auto* s = static_cast<Store*>(p);
  std::lock_guard<std::mutex> g(s->mu);
  std::string k = key_of(hash);
  s->offloaded.erase(k);
  if (!s->hash_index.count(k)) {
    s->removed.insert(k);
    s->stored.erase(k);
  }
}

void xbs_record_offload(void* p, const char* hash, int tier) {
  auto* s = static_cast<Store*>(p);
  std::lock_guard<std::mutex> g(s->mu);
  std::string k = key_of(hash);
  if (s->hash_index.count(k)) return;  // hot tier stays authoritative
  s->offloaded[k] = tier;
  s->removed.erase(k);
  s->stored.erase(k);
}

// Post-eviction accounting for xbs_allocate's victims: saved ones become
// offload events, the rest removals.
void xbs_record_evicted(void* p, const char* hash, int saved_tier /*-1=no*/) {
  auto* s = static_cast<Store*>(p);
  std::lock_guard<std::mutex> g(s->mu);
  std::string k = key_of(hash);
  if (saved_tier >= 0) {
    s->offloaded[k] = saved_tier;
    s->removed.erase(k);
  } else {
    s->removed.insert(k);
  }
  s->stored.erase(k);
}

void xbs_event_counts(void* p, int* n_stored, int* n_removed, int* n_offload) {
  auto* s = static_cast<Store*>(p);
  std::lock_guard<std::mutex> g(s->mu);
  *n_stored = static_cast<int>(s->stored.size());
  *n_removed = static_cast<int>(s->removed.size());
  *n_offload = static_cast<int>(s->offloaded.size());
}

// Drain events. Buffers hold `cap_*` 16-byte hashes (+ tiers). Returns 0 and
// drains when everything fits, else -1 and drains NOTHING (retry bigger).
int xbs_take_events(void* p, char* stored_buf, int cap_stored, int* n_stored,
                    char* removed_buf, int cap_removed, int* n_removed,
                    char* offload_buf, int32_t* offload_tiers, int cap_offload,
                    int* n_offload) {
  auto* s = static_cast<Store*>(p);
  std::lock_guard<std::mutex> g(s->mu);
  if (static_cast<int>(s->stored.size()) > cap_stored ||
      static_cast<int>(s->removed.size()) > cap_removed ||
      static_cast<int>(s->offloaded.size()) > cap_offload) {
    *n_stored = static_cast<int>(s->stored.size());
    *n_removed = static_cast<int>(s->removed.size());
    *n_offload = static_cast<int>(s->offloaded.size());
    return -1;
  }
  int i = 0;
  for (const auto& k : s->stored)
    std::memcpy(stored_buf + (i++) * kHashLen, k.data(), kHashLen);
  *n_stored = i;
  i = 0;
  for (const auto& k : s->removed)
    std::memcpy(removed_buf + (i++) * kHashLen, k.data(), kHashLen);
  *n_removed = i;
  i = 0;
  for (const auto& kv : s->offloaded) {
    std::memcpy(offload_buf + i * kHashLen, kv.first.data(), kHashLen);
    offload_tiers[i++] = kv.second;
  }
  *n_offload = i;
  s->stored.clear();
  s->removed.clear();
  s->offloaded.clear();
  return 0;
}

}  // extern "C"
