"""Device-mesh construction for an engine instance.

TPU-native replacement for the reference engine's NCCL/MPI process groups
(SURVEY.md §2.2): parallelism is expressed as a `jax.sharding.Mesh` with
named axes and sharding annotations; XLA inserts the ICI/DCN collectives.

Axes:
  dp — data parallel (decode batch rows, independent replicas)
  tp — tensor parallel (attention heads / FFN hidden)
  (later rounds add: ep — expert parallel; sp — sequence/context parallel)
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def build_mesh(
    dp: int = 1,
    tp: int = 1,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    if devices is None:
        devices = jax.devices()
    need = dp * tp
    if need > len(devices):
        raise ValueError(f"mesh dp*tp={need} exceeds {len(devices)} devices")
    arr = np.asarray(devices[:need]).reshape(dp, tp)
    return Mesh(arr, ("dp", "tp"))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def shard(mesh: Mesh, *spec) -> NamedSharding:
    return NamedSharding(mesh, P(*spec))
