"""Device-mesh construction for an engine instance.

TPU-native replacement for the reference engine's NCCL/MPI process groups
(SURVEY.md §2.2): parallelism is expressed as a `jax.sharding.Mesh` with
named axes and sharding annotations; XLA inserts the ICI/DCN collectives.

Axes:
  dp — data parallel (decode batch rows, independent replicas)
  tp — tensor parallel (attention heads / FFN hidden)
  ep — expert parallel (MoE expert shards; models/llama.py's combine
       contraction makes XLA emit the psum)
  sp — sequence/context parallel (ring attention, ops/ring_attention.py)
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def build_mesh(
    dp: int = 1,
    tp: int = 1,
    ep: int = 1,
    sp: int = 1,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Axes appear only when sized > 1 (existing shardings reference axes
    by name and tolerate absence); tp stays innermost: per-layer TP psums
    (the most latency-sensitive collectives) ride CONTIGUOUS ICI
    neighbors, while sp's ring ppermute and ep's per-MLP psum tolerate the
    larger stride."""
    if devices is None:
        devices = jax.devices()
    need = dp * tp * ep * sp
    if need > len(devices):
        raise ValueError(
            f"mesh dp*tp*ep*sp={need} exceeds {len(devices)} devices"
        )
    sizes = [("dp", dp), ("sp", sp), ("ep", ep), ("tp", tp)]
    names = tuple(n for n, s in sizes if s > 1 or n in ("dp", "tp"))
    dims = tuple(s for n, s in sizes if s > 1 or n in ("dp", "tp"))
    arr = np.asarray(devices[:need]).reshape(dims)
    return Mesh(arr, names)


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def shard(mesh: Mesh, *spec) -> NamedSharding:
    return NamedSharding(mesh, P(*spec))
