"""Per-shard KV wire payloads for the sharded engine tier.

Every KV movement plane — the PD handoff (monolithic and streamed
chunks), the prefix-fabric `/kv/fetch`, and the coordinated-eviction
re-homing frames — carries migration payloads shaped
`[num_caches, L, n_blocks, Hc, BS, D]` with the cache-head axis (3)
sharded over `tp` on a multi-chip engine (parallel/sharding.py
kv_cache_sharding). Before this module, those planes shipped and landed
the payload as ONE flat array: `np.asarray` on the sender was a
cross-shard host GATHER, and the consumer re-sharded on import — two
host↔device bounces per handoff that exist only because the wire format
didn't know the cache was sharded.

`ShardedKV` keeps the payload as per-shard pieces end-to-end:

  * a tp=N holder exports N per-shard block sets (`to_host` reads each
    shard's host copy straight off its own device — no gather);
  * the frame protocol (api/protocol.py kv_frame_to_bytes/kv_frame_array)
    serializes the pieces back-to-back with a `kv_shards` header;
  * the consumer lands them with `assemble` /
    `jax.make_array_from_callback` directly onto ITS
    `kv_cache_sharding`-derived payload sharding (runtime/executor.py
    migration_sharding) — `jax.device_put` per shard, no host concat
    when the shard boundaries line up (the common same-tp PD pair), a
    minimal per-boundary concat when they don't (tp=4 holder → tp=2
    consumer).

On a 1-device engine every function here degenerates to the old flat
np.ndarray behavior, so unsharded deployments see byte-identical wires.
`np.asarray(ShardedKV)` concatenates (compat escape for host tiers and
tests); `.shape` is the LOGICAL full shape so every existing
`migration_shape` gate keeps working unchanged.
"""

from __future__ import annotations

from typing import List, Sequence

import numpy as np

# The cache-head axis of a migration payload [num_caches, L, n, Hc, BS, D].
HEAD_AXIS = 3


class ShardedKV:
    """A KV migration payload held as per-shard pieces along HEAD_AXIS.

    `shards[i]` is the i-th tp shard's slice (host np.ndarray on the
    wire; device pieces are converted by `to_host`). Supports the small
    surface the KV planes actually use: `.shape`/`.dtype` (logical),
    `np.asarray` (concat compat), leading-axis `__getitem__` (block
    sub-selection, applied per shard), and `.nbytes`.
    """

    axis = HEAD_AXIS

    def __init__(self, shards: Sequence[np.ndarray]):
        if not shards:
            raise ValueError("ShardedKV needs at least one shard")
        self.shards: List[np.ndarray] = list(shards)

    @property
    def shape(self):
        s0 = self.shards[0].shape
        heads = sum(s.shape[self.axis] for s in self.shards)
        return tuple(
            heads if i == self.axis else d for i, d in enumerate(s0)
        )

    @property
    def dtype(self):
        return self.shards[0].dtype

    @property
    def nbytes(self) -> int:
        return sum(int(np.asarray(s).nbytes) for s in self.shards)

    @property
    def head_sizes(self) -> List[int]:
        return [int(s.shape[self.axis]) for s in self.shards]

    def __getitem__(self, idx):
        """Apply a leading-axes index (block sub-selection like
        `kv[:, :, fresh]`) to every shard. The index must not touch the
        head axis — the planes never do — and must not DROP an axis
        (a bare integer would shift the head axis left and silently
        corrupt `.shape`; use a length-1 slice/array instead)."""
        entries = idx if isinstance(idx, tuple) else (idx,)
        if len(entries) > self.axis:
            raise IndexError(
                "ShardedKV indexing must stay on the leading "
                f"{self.axis} axes"
            )
        if any(isinstance(e, (int, np.integer)) for e in entries):
            raise IndexError(
                "ShardedKV rejects integer indices (they would remove "
                "an axis and shift the head axis); use a slice or an "
                "index array"
            )
        return ShardedKV([np.asarray(s)[idx] for s in self.shards])

    def __array__(self, dtype=None, copy=None):
        out = np.concatenate(
            [np.asarray(s) for s in self.shards], axis=self.axis
        )
        return out.astype(dtype) if dtype is not None else out

    def tobytes(self) -> bytes:
        return b"".join(np.ascontiguousarray(s).tobytes() for s in self.shards)


def to_host(kv):
    """Device payload → host wire form WITHOUT a cross-shard gather.

    A jax.Array sharded along HEAD_AXIS becomes a `ShardedKV` of each
    shard's own host copy (replicas — a dp axis — are deduplicated; the
    first addressable replica of each head range wins). Anything else
    (np.ndarray, single-device/replicated arrays, an already-host
    ShardedKV) passes through as the flat host array the old wire
    carried."""
    if isinstance(kv, ShardedKV):
        return kv
    shards = getattr(kv, "addressable_shards", None)
    if shards is None or getattr(kv, "ndim", 0) <= HEAD_AXIS:
        return np.asarray(kv)
    # Two passes so the flat-array cases never pay an extra copy: first
    # classify the layout from shard INDICES alone, and only when the
    # head axis is genuinely split (>= 2 distinct ranges) read each
    # piece's host copy — a single-shard/replicated array (the default
    # tp=1 deployment) goes straight to the one np.asarray it always
    # paid.
    chosen = {}
    for s in shards:
        idx = s.index
        # Only pure head-axis sharding rides the per-shard wire: any
        # other partitioned axis (a sliced leading dim) means this
        # payload isn't the KV-plane layout — gather and move on.
        for ax, sl in enumerate(idx):
            if ax != HEAD_AXIS and sl != slice(None, None, None):
                return np.asarray(kv)
        lo = idx[HEAD_AXIS].start or 0
        if lo not in chosen:
            chosen[lo] = s
    if len(chosen) <= 1:
        return np.asarray(kv)
    pieces = {lo: np.asarray(s.data) for lo, s in chosen.items()}
    covered = sum(p.shape[HEAD_AXIS] for p in pieces.values())
    if covered != kv.shape[HEAD_AXIS]:
        # Multi-process mesh: this process holds only some shards, so
        # the per-shard wire can't be assembled here. np.asarray on a
        # non-fully-addressable array RAISES — exactly what the
        # pre-shard wire did on this path (the send machinery fails the
        # session / errors the handoff); cross-host exports are a
        # future arc, engines today are per-host.
        return np.asarray(kv)
    return ShardedKV([pieces[k] for k in sorted(pieces)])


def assemble(kv, sharding):
    """Land a wire payload directly onto a consumer sharding.

    `kv` may be a ShardedKV (per-shard pieces), a host np.ndarray, or a
    device array from another mesh. Returns a committed jax.Array with
    `sharding`. For ShardedKV whose piece boundaries align with the
    consumer's partition (the same-tp PD pair), each device's buffer is
    fed from its own piece — no host concat of the full payload ever
    materializes; mismatched boundaries concat only the pieces that
    straddle them."""
    import jax

    if not isinstance(kv, ShardedKV):
        arr = kv if isinstance(kv, jax.Array) else np.asarray(kv)
        return jax.device_put(arr, sharding)
    shards = [np.asarray(s) for s in kv.shards]
    offs = np.cumsum([0] + [s.shape[HEAD_AXIS] for s in shards])
    shape = kv.shape

    def cb(index):
        sl = index[HEAD_AXIS]
        lo = sl.start or 0
        hi = sl.stop if sl.stop is not None else shape[HEAD_AXIS]
        parts = []
        for i, s in enumerate(shards):
            s_lo, s_hi = int(offs[i]), int(offs[i + 1])
            if s_hi <= lo or s_lo >= hi:
                continue
            a, b = max(lo, s_lo) - s_lo, min(hi, s_hi) - s_lo
            parts.append(
                s[(slice(None),) * HEAD_AXIS + (slice(a, b),)]
            )
        arr = parts[0] if len(parts) == 1 else np.concatenate(
            parts, axis=HEAD_AXIS
        )
        rest = tuple(
            sl_ if ax != HEAD_AXIS else slice(None)
            for ax, sl_ in enumerate(index)
        )
        return arr[rest]

    return jax.make_array_from_callback(shape, sharding, cb)
