"""Megatron-style tensor-parallel sharding specs for the Llama param pytree.

The per-layer tensors carry a leading stacked-layer axis (models/llama.py),
so specs shift right by one. Contract:

  wq/wk/wv  [L, E, H*D]   → shard output heads over tp
  wo        [L, H*D, E]   → shard contracting dim over tp (psum after)
  w_gate/up [L, E, F]     → shard F; w_down [L, F, E] → shard F
  MoE       experts axis X over tp for now (true `ep` axis in later rounds)
  embed     [V, E]        → shard V (all-gather on embed lookup is tiny)
  lm_head   [E, V]        → shard V
  KV caches [L, B, bs, Hkv, D] → shard Hkv over tp

XLA derives the matching collectives (psum for row-parallel contractions)
from these annotations under jit — no hand-written comms.
"""

from __future__ import annotations

from typing import Any, Dict

from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from xllm_service_tpu.models.configs import ModelConfig


def param_shardings(cfg: ModelConfig, mesh: Mesh) -> Dict[str, Any]:
    def ns(*spec):
        return NamedSharding(mesh, P(*spec))

    layers: Dict[str, Any] = {
        "attn_norm": ns(None, None),
        "wq": ns(None, None, "tp"),
        "wk": ns(None, None, "tp"),
        "wv": ns(None, None, "tp"),
        "wo": ns(None, "tp", None),
        "mlp_norm": ns(None, None),
    }
    if cfg.is_moe:
        layers.update(
            {
                "router": ns(None, None, None),
                "w_gate": ns(None, "tp", None, None),
                "w_up": ns(None, "tp", None, None),
                "w_down": ns(None, "tp", None, None),
            }
        )
    else:
        layers.update(
            {
                "w_gate": ns(None, None, "tp"),
                "w_up": ns(None, None, "tp"),
                "w_down": ns(None, "tp", None),
            }
        )
    out: Dict[str, Any] = {
        "embed": ns("tp", None),
        "layers": layers,
        "final_norm": ns(None),
    }
    if not cfg.tie_word_embeddings:
        out["lm_head"] = ns(None, "tp")
    return out


def kv_cache_sharding(mesh: Mesh) -> NamedSharding:
    # [L, num_blocks, bs, Hkv, D]: KV heads over tp.
    return NamedSharding(mesh, P(None, None, None, "tp", None))


def check_tp_divisibility(cfg: ModelConfig, tp: int) -> None:
    if cfg.num_kv_heads % tp or cfg.num_heads % tp:
        raise ValueError(
            f"tp={tp} must divide num_heads={cfg.num_heads} and "
            f"num_kv_heads={cfg.num_kv_heads}"
        )
    if cfg.intermediate_size % tp:
        raise ValueError(f"tp={tp} must divide intermediate={cfg.intermediate_size}")
