"""Megatron-style tensor-parallel sharding specs for the Llama param pytree.

The per-layer tensors carry a leading stacked-layer axis (models/llama.py),
so specs shift right by one. Contract:

  wq/wk/wv  [L, E, H*D]   → shard output heads over tp
  wo        [L, H*D, E]   → shard contracting dim over tp (psum after)
  dense MLP w_gate/up [L, E, F] → shard F; w_down [L, F, E] → shard F
  MoE       w_gate/up [L, X, E, Fm], w_down [L, X, Fm, E] → experts X over
            ep (when an ep mesh axis is given) and Fm over tp; without an
            ep axis, X rides tp (pure-TP MoE for small expert counts)
  embed     [V, E]        → shard V (all-gather on embed lookup is tiny)
  lm_head   [E, V]        → shard V
  KV caches [L, B, bs, Hkv, D] → shard Hkv over tp

XLA derives the matching collectives (psum for row-parallel contractions)
from these annotations under jit — no hand-written comms.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict

from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from xllm_service_tpu.models.configs import ModelConfig


def param_shardings(
    cfg: ModelConfig,
    mesh: Mesh,
    tp_axis: str = "tp",
    ep_axis: str | None = None,
) -> Dict[str, Any]:
    """Sharding pytree matching the Llama param pytree.

    `ep_axis` (when set and present in the mesh) shards the MoE expert axis
    over its own mesh axis while `tp_axis` shards each expert's hidden dim —
    true EP×TP. With ep_axis=None, experts ride the tp axis (pure-TP MoE,
    right for small expert counts on one slice)."""

    def ns(*spec):
        return NamedSharding(mesh, P(*spec))

    tp = tp_axis if tp_axis in mesh.shape else None
    layers: Dict[str, Any] = {
        "attn_norm": ns(None, None),
        "mlp_norm": ns(None, None),
    }
    if cfg.is_mla:
        # MLA (deepseek.py): the shared latent path (w_dq/w_dkv and norms)
        # replicates — it is tiny and feeds every head; the per-head
        # up-projections and wo shard over heads (Megatron column/row).
        layers.update(
            {
                "w_dkv": ns(None, None, None),
                "kv_norm": ns(None, None),
                "w_uk": ns(None, tp, None, None),
                "w_uv": ns(None, tp, None, None),
                "wo": ns(None, tp, None),
            }
        )
        if cfg.q_lora_rank > 0:
            layers.update(
                {
                    "w_dq": ns(None, None, None),
                    "q_norm": ns(None, None),
                    "w_uq": ns(None, None, tp),
                }
            )
        else:
            layers["w_q"] = ns(None, None, tp)
    else:
        layers.update(
            {
                "wq": ns(None, None, tp),
                "wk": ns(None, None, tp),
                "wv": ns(None, None, tp),
                "wo": ns(None, tp, None),
            }
        )
        if cfg.attn_bias:
            layers.update(
                {"bq": ns(None, tp), "bk": ns(None, tp), "bv": ns(None, tp)}
            )
        if cfg.qk_norm:
            # Head-dim norms are tiny and head-agnostic: replicate.
            layers.update(
                {
                    "q_head_norm": ns(None, None),
                    "k_head_norm": ns(None, None),
                }
            )
    if cfg.is_moe:
        ep = ep_axis if ep_axis is not None and ep_axis in mesh.shape else None
        e, t = (ep, tp) if ep is not None else (tp, None)
        layers.update(
            {
                "router": ns(None, None, None),
                "w_gate": ns(None, e, None, t),
                "w_up": ns(None, e, None, t),
                "w_down": ns(None, e, t, None),
            }
        )
        if cfg.topk_method == "noaux_tc":
            layers["router_bias"] = ns(None, None)  # replicated like router
        if cfg.n_shared_experts > 0:
            # DeepSeek shared experts: dense SwiGLU, ordinary column/row TP.
            layers.update(
                {
                    "w_sh_gate": ns(None, None, tp),
                    "w_sh_up": ns(None, None, tp),
                    "w_sh_down": ns(None, tp, None),
                }
            )
    else:
        layers.update(
            {
                "w_gate": ns(None, None, tp),
                "w_up": ns(None, None, tp),
                "w_down": ns(None, tp, None),
            }
        )
    out: Dict[str, Any] = {
        "embed": ns(tp, None),
        "layers": layers,
        "final_norm": ns(None),
    }
    if cfg.first_k_dense_replace > 0:
        # Heterogeneous DeepSeek stack: the dense prefix carries the same
        # MLA attention specs plus dense-SwiGLU MLP specs (models/deepseek
        # _layer_stack(moe=False)).
        dense = {
            k: v
            for k, v in layers.items()
            if k
            not in (
                "router", "router_bias", "w_gate", "w_up", "w_down",
                "w_sh_gate", "w_sh_up", "w_sh_down",
            )
        }
        dense.update(
            {
                "w_gate": ns(None, None, tp),
                "w_up": ns(None, None, tp),
                "w_down": ns(None, tp, None),
            }
        )
        out["dense_layers"] = dense
    if not cfg.tie_word_embeddings:
        out["lm_head"] = ns(None, tp)
    return out


def kv_cache_sharding(mesh: Mesh) -> NamedSharding:
    # [L, num_blocks, Hkv, bs, D]: KV heads over tp.
    return NamedSharding(mesh, P(None, None, "tp", None, None))


def kv_scale_sharding(mesh: Mesh) -> NamedSharding:
    # int8 cache scales [L, num_blocks, Hkv, G, bs]: KV heads over tp,
    # same placement as the data rows they scale (G = sub-channel groups,
    # a multiple of 8 so the per-block [G, bs] DMA tile is Mosaic-legal
    # on every tp shard — see ops/kv_cache.py).
    return NamedSharding(mesh, P(None, None, "tp", None, None))


def check_tp_divisibility(cfg: ModelConfig, tp: int, ep: int = 1) -> None:
    if cfg.is_mla:
        # MLA: only query heads shard (the latent cache is shared/replicated).
        if cfg.num_heads % tp:
            raise ValueError(
                f"tp={tp} must divide num_heads={cfg.num_heads}"
            )
    elif cfg.num_kv_heads % tp or cfg.num_heads % tp:
        raise ValueError(
            f"tp={tp} must divide num_heads={cfg.num_heads} and "
            f"num_kv_heads={cfg.num_kv_heads}"
        )
    # head_dim<128 packed rows cap the shardable cache-head axis at the
    # packed count; when tp doesn't divide it the executor falls back to
    # the unpacked layout via resolve_kv_packing (ADVICE r3) instead of
    # rejecting the config here.
    if cfg.is_moe:
        _check_moe_divisibility(cfg, tp, ep)
    elif cfg.intermediate_size % tp:
        raise ValueError(
            f"tp={tp} must divide intermediate={cfg.intermediate_size}"
        )


def resolve_kv_packing(cfg: ModelConfig, tp: int) -> ModelConfig:
    """Disable head_dim<128 row packing when tp doesn't divide the packed
    head count (e.g. llama-1B-class: Hkv=8, D=64 packs to 4 rows, so tp=8
    only works unpacked). The unpacked cache keeps the gather attention
    path functional; packing (and kernel eligibility) is purely a layout
    optimization, never a correctness requirement."""
    from xllm_service_tpu.ops.kv_cache import kv_pack_factor

    if cfg.is_mla or cfg.kv_pack_disable:
        return cfg
    pf = kv_pack_factor(cfg.num_kv_heads, cfg.head_dim)
    if pf > 1 and (cfg.num_kv_heads // pf) % tp:
        return dataclasses.replace(cfg, kv_pack_disable=True)
    return cfg


def _check_moe_divisibility(cfg: ModelConfig, tp: int, ep: int) -> None:
    # EP×TP: experts over ep, per-expert hidden over tp; pure-TP MoE
    # (ep=1) shards the expert axis over tp instead.
    if ep > 1:
        if cfg.num_experts % ep:
            raise ValueError(
                f"ep={ep} must divide num_experts={cfg.num_experts}"
            )
        if cfg.moe_intermediate_size % tp:
            raise ValueError(
                f"tp={tp} must divide "
                f"moe_intermediate={cfg.moe_intermediate_size}"
            )
    elif cfg.num_experts % tp:
        raise ValueError(
            f"tp={tp} must divide num_experts={cfg.num_experts}"
        )
    # Heterogeneous stack: the dense prefix shards intermediate_size.
    if cfg.first_k_dense_replace > 0 and cfg.intermediate_size % tp:
        raise ValueError(
            f"tp={tp} must divide dense-prefix intermediate="
            f"{cfg.intermediate_size}"
        )
