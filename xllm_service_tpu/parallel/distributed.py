"""Multi-host process-group bootstrap.

The reference scales across hosts through its NCCL/MPI-backed engine
backend (absent submodule; the service relays per-node addrs for it —
SURVEY.md §2.2 comm backends). The TPU-native equivalent is
`jax.distributed`: every host process calls initialize() against one
coordinator, after which `jax.devices()` is the GLOBAL device list and a
`jax.sharding.Mesh` over it spans the pod — XLA's SPMD partitioner then
rides ICI within a slice and DCN across hosts with no hand-written
communication. A v5e-64 (16 hosts x 4 chips) mesh exists only after this
bootstrap.

Config: EngineConfig.coordinator_address / num_processes / process_id
(process_id < 0 means single-process; on real TPU pods num_processes and
process_id may be omitted and are discovered from the TPU metadata).
"""

from __future__ import annotations

import logging
import threading

logger = logging.getLogger(__name__)

_BOOT_MU = threading.Lock()
_BOOTED = False


def bootstrap(
    coordinator_address: str,
    num_processes: int = 0,
    process_id: int = -1,
) -> bool:
    """Idempotently initialize jax.distributed. Returns True when this
    call (or a previous one) initialized the process group; False when
    coordinator_address is empty (single-process mode).

    MUST run before the first JAX backend touch in the process — the
    executor calls it before building its mesh.
    """
    global _BOOTED
    if not coordinator_address:
        return False
    with _BOOT_MU:
        if _BOOTED:
            return True
        import jax

        kwargs = {}
        if num_processes > 0:
            kwargs["num_processes"] = num_processes
        if process_id >= 0:
            kwargs["process_id"] = process_id
        jax.distributed.initialize(coordinator_address, **kwargs)
        _BOOTED = True
        logger.info(
            "jax.distributed up: coordinator=%s process=%s/%s global_devices=%d",
            coordinator_address,
            jax.process_index(),
            jax.process_count(),
            len(jax.devices()),
        )
        return True


def is_bootstrapped() -> bool:
    return _BOOTED
