"""Pipeline parallelism: GPipe-schedule dense forward over a `pp` mesh
axis.

The LAST absent row of SURVEY.md §2.2 — absent everywhere in the
reference too ("None anywhere"), deprioritized by three verdicts, and
closed here at the level the reference family actually uses pipelines:
a stage-sharded forward for prefill/training-shaped work. (PP for
autoregressive DECODE serving trades per-token latency for nothing at
this scale — tp/sp/dp/ep already cover the serving meshes; the
reference ships no PP at all.)

TPU-first design: the stacked layer leaves [L, ...] shard over the
`pp` axis on the LAYER dimension (stage s holds layers
[s*L/S, (s+1)*L/S)); one `shard_map` program runs the classic GPipe
schedule — S + M - 1 ticks over M microbatches, each tick applying the
device's local layer stack (a lax.scan) and rotating activations one
stage forward with `lax.ppermute` over ICI. Every device executes the
same fixed-shape program (inactive ticks compute on garbage and are
masked), so XLA compiles ONE step body; bubbles follow the standard
(S - 1) / (S + M - 1) fraction.

Exactness: output logits equal models/llama.forward_dense on the same
params (parity-pinned in tests/test_pipeline.py and the driver dryrun).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from xllm_service_tpu.models.configs import ModelConfig


def pipeline_param_shardings(cfg: ModelConfig, mesh: Mesh,
                             pp_axis: str = "pp"):
    """NamedShardings for the llama param pytree with the stacked layer
    leaves split over `pp_axis` on the layer axis; everything else
    replicated (stage 0 embeds, the last stage unembeds)."""
    from xllm_service_tpu.models import llama

    shapes = jax.eval_shape(
        lambda k: llama.init_params(cfg, k, jnp.float32),
        jax.random.key(0),
    )
    rep = NamedSharding(mesh, P())
    layer = NamedSharding(mesh, P(pp_axis))
    return {
        k: jax.tree.map(lambda _: layer if k == "layers" else rep, v)
        for k, v in shapes.items()
    }


def _apply_local_layers(lp_local, cfg: ModelConfig, x: jnp.ndarray,
                        positions: jnp.ndarray,
                        causal: jnp.ndarray) -> jnp.ndarray:
    """Scan this stage's layer slice over activations [b, Lq, E] — the
    same dense layer body as llama.hidden_dense."""
    from xllm_service_tpu.models.llama import _mlp_block, _qkv
    from xllm_service_tpu.ops.norms import rms_norm
    from xllm_service_tpu.ops.quant import wt

    scale = cfg.head_dim**-0.5
    Lq = x.shape[1]
    Hq, Hkv, D = cfg.num_heads, cfg.num_kv_heads, cfg.head_dim
    g = Hq // Hkv

    def layer_fn(x, lp):
        h = rms_norm(x, lp["attn_norm"], cfg.rms_norm_eps)

        def one_seq(hx):
            q, k, v = _qkv(lp, cfg, hx, positions)
            qf = q.astype(jnp.float32).reshape(Lq, Hkv, g, D)
            scores = jnp.einsum(
                "qhgd,khd->hgqk", qf, k.astype(jnp.float32)
            ) * scale
            scores = jnp.where(causal[None, None], scores, -1e30)
            probs = jax.nn.softmax(scores, axis=-1)
            attn = jnp.einsum(
                "hgqk,khd->qhgd", probs, v.astype(jnp.float32)
            )
            return attn.reshape(Lq, Hq * D).astype(x.dtype)

        attn = jax.vmap(one_seq)(h)
        # wt() dequantizes int8/int4 leaves at the use site (and is the
        # identity on plain arrays) — same contract as llama.hidden_dense.
        wo = wt(lp["wo"])
        x = x + jnp.einsum(
            "ble,ef->blf", attn,
            wo.astype(attn.dtype) if wo.dtype != attn.dtype else wo,
        )
        h = rms_norm(x, lp["mlp_norm"], cfg.rms_norm_eps)
        # _mlp_block keeps this body on the exact dense per-row program
        # by default and in lockstep with llama.hidden_dense (whose twin
        # this is) when the grouped-MoE dispatch is enabled — full-length
        # prompts here, every row live.
        x = x + _mlp_block(lp, cfg, h)
        return x, None

    x, _ = jax.lax.scan(layer_fn, x, lp_local)
    return x


def pipeline_forward_dense(
    params,
    cfg: ModelConfig,
    token_ids: jnp.ndarray,  # [B, Lq] int32, B % microbatches == 0
    mesh: Mesh,
    pp_axis: str = "pp",
    microbatches: int = 2,
) -> jnp.ndarray:
    """[B, Lq] -> logits [B, Lq, V], exactly llama.forward_dense, with
    the layer stack pipelined over `mesh`'s `pp_axis`. Call under jit
    with the mesh installed and params placed per
    pipeline_param_shardings."""
    from xllm_service_tpu.models.llama import _embed, _project
    from xllm_service_tpu.ops.norms import rms_norm
    from xllm_service_tpu.ops.quant import wdtype

    S = mesh.shape[pp_axis]
    B, Lq = token_ids.shape
    M = microbatches
    assert B % M == 0, (B, M)
    b = B // M
    positions = jnp.arange(Lq, dtype=jnp.int32)
    causal = jnp.tril(jnp.ones((Lq, Lq), dtype=bool))
    if cfg.sliding_window:
        causal &= (
            positions[None, :] > positions[:, None] - cfg.sliding_window
        )

    def local(layers_local, embed_w, final_norm, head_or_embed,
              token_ids):
        d = jax.lax.axis_index(pp_axis)
        full = {"embed": embed_w, "layers": None}
        x_mb = _embed(full, cfg, token_ids, wdtype(embed_w)).reshape(
            M, b, Lq, -1
        )
        E = x_mb.shape[-1]
        perm = [(i, (i + 1) % S) for i in range(S)]
        out0 = jnp.zeros((M, b, Lq, E), x_mb.dtype)
        recv0 = jnp.zeros((b, Lq, E), x_mb.dtype)

        def tick(carry, t):
            recv, outs = carry
            j = t - d  # this device's microbatch index this tick
            valid = (j >= 0) & (j < M)
            jc = jnp.clip(j, 0, M - 1)
            x_in = jnp.where(d == 0, x_mb[jc], recv)
            y = _apply_local_layers(
                layers_local, cfg, x_in, positions, causal
            )
            outs = jnp.where(
                valid & (d == S - 1),
                outs.at[jc].set(y),
                outs,
            )
            recv = jax.lax.ppermute(y, pp_axis, perm)
            return (recv, outs), None

        (_, outs), _ = jax.lax.scan(
            tick, (recv0, out0), jnp.arange(S + M - 1, dtype=jnp.int32)
        )
        # Only the last stage holds real outputs; replicate via psum.
        outs = jnp.where(d == S - 1, outs, jnp.zeros_like(outs))
        outs = jax.lax.psum(outs, pp_axis)
        h = rms_norm(
            outs.reshape(B, Lq, E), final_norm, cfg.rms_norm_eps
        )
        full2 = (
            {"embed": head_or_embed} if cfg.tie_word_embeddings
            else {"lm_head": head_or_embed, "embed": embed_w}
        )
        return _project(full2, cfg, h)

    head = (
        params["embed"] if cfg.tie_word_embeddings else params["lm_head"]
    )
    rep = P()
    in_specs = (
        jax.tree.map(lambda _: P(pp_axis), params["layers"]),
        rep, rep, rep, rep,
    )
    if hasattr(jax, "shard_map"):
        fn = jax.shard_map(
            local, mesh=mesh, in_specs=in_specs, out_specs=rep,
            check_vma=False,
        )
    else:  # jax < 0.6: the API (and the check_vma knob, née check_rep)
        from jax.experimental.shard_map import shard_map as _shard_map

        fn = _shard_map(
            local, mesh, in_specs=in_specs, out_specs=rep, check_rep=False
        )
    return fn(
        params["layers"], params["embed"], params["final_norm"], head,
        token_ids,
    )
