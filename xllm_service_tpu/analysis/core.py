"""graftlint core: one lint framework for the project's bespoke checkers.

The system is deeply concurrent — ~100 threading.Lock/RLock/Condition/
Event/Thread sites across the evserve loop, engine hot loop, transfer/
stream lanes, encoder batcher, and heartbeat/election threads — and the
reference paper's service tier leans on C++ TSan/clang-tidy for exactly
the bug class our recent review fixes kept catching by hand (join-
unstarted races, double-unwind, blocking RPC under a lock). This package
is the Python answer: a single AST-walking framework with pluggable
passes, run repo-wide by `scripts/graftlint.py --all` and enforced as a
tier-1 test (tests/test_graftlint.py).

Vocabulary shared by every pass:

* a `Source` is one parsed file (text + lines + lazily parsed AST +
  waiver map);
* a `Project` is the set of sources a pass may look at — the package,
  the bench entry points, the tests (raw text, for coverage checks),
  and the docs (for registry cross-checks). `Project.from_sources`
  builds a synthetic in-memory project so each pass is unit-testable
  against fixture snippets without touching disk;
* a `Finding` is one violation, anchored to a file:line;
* a **waiver** is a trailing comment on the finding's anchor line:

      # graftlint: allow=<pass-id>[,<pass-id>] -- <why this is safe>

  The framework drops waived findings and reports how many waivers
  fired; a waiver naming a pass that never finds anything on that line
  is itself a finding (stale waivers rot like stale comments).

Passes live in sibling modules; `xllm_service_tpu.analysis` exports the
canonical `ALL_PASSES` list. The three legacy checkers
(scripts/check_metric_names.py, check_fault_points.py,
check_kernel_hatches.py) are thin shims over their absorbed passes —
one framework, no dual maintenance (docs/STATIC_ANALYSIS.md).
"""

from __future__ import annotations

import ast
import os
import re
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

__all__ = [
    "Finding",
    "Source",
    "Project",
    "LintPass",
    "run_passes",
    "WAIVER_RE",
]

# Trailing-comment waiver: `# graftlint: allow=blocking-under-lock -- why`.
WAIVER_RE = re.compile(r"#\s*graftlint:\s*allow=([a-z0-9_,-]+)")

# Method-level annotation: `def f(self):  # graftlint: holds=self._lock`
# asserts the caller contract "only invoked with self._lock held", so the
# lock-discipline pass treats the whole body as guarded by that lock.
HOLDS_RE = re.compile(r"#\s*graftlint:\s*holds=self\.([A-Za-z_][A-Za-z0-9_]*)")

# Field annotation: `self._waiting = deque()  # guarded by: self._lock`.
GUARDED_BY_RE = re.compile(
    r"#\s*guarded by:\s*self\.([A-Za-z_][A-Za-z0-9_]*)"
)

# Method annotation: `def _init_mm(self):  # graftlint: init-only` marks a
# constructor extension (mixin `_init_*` methods called only from
# __init__) — no concurrent peer can exist yet, so the lock-discipline
# pass exempts it like __init__ itself.
INIT_ONLY_RE = re.compile(r"#\s*graftlint:\s*init-only")


@dataclass(frozen=True)
class Finding:
    """One violation. `line` anchors the waiver lookup."""

    pass_id: str
    path: str  # repo-relative
    line: int
    message: str

    def render(self) -> str:
        return f"{self.path}:{self.line}: [{self.pass_id}] {self.message}"


class Source:
    """One file: text, split lines, lazily parsed AST, waiver map."""

    def __init__(self, rel: str, text: str):
        self.rel = rel
        self.text = text
        self.lines = text.splitlines()
        self._tree: Optional[ast.Module] = None
        self._parse_error: Optional[str] = None
        self._waivers: Optional[Dict[int, Set[str]]] = None

    @property
    def tree(self) -> Optional[ast.Module]:
        if self._tree is None and self._parse_error is None:
            try:
                self._tree = ast.parse(self.text)
            except SyntaxError as e:  # pragma: no cover — repo parses
                self._parse_error = str(e)
        return self._tree

    @property
    def parse_error(self) -> Optional[str]:
        self.tree
        return self._parse_error

    def line_comment(self, lineno: int) -> str:
        """The raw text of line `lineno` (1-based); '' when out of range.

        Good enough for trailing-comment annotations: none of our
        annotated lines put the marker text inside a string literal.
        """
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1]
        return ""

    @property
    def waivers(self) -> Dict[int, Set[str]]:
        """{lineno: {pass ids}} for every graftlint allow= comment."""
        if self._waivers is None:
            w: Dict[int, Set[str]] = {}
            for i, line in enumerate(self.lines, start=1):
                m = WAIVER_RE.search(line)
                if m:
                    w[i] = {p.strip() for p in m.group(1).split(",") if p.strip()}
            self._waivers = w
        return self._waivers


def _walk_py(root: str) -> Iterable[str]:
    for dirpath, dirs, files in os.walk(root):
        if "__pycache__" in dirpath:
            continue
        for fn in sorted(files):
            if fn.endswith(".py"):
                yield os.path.join(dirpath, fn)


class Project:
    """What the passes see. Three source groups plus the docs:

    * `sources` — the package proper (`xllm_service_tpu/**.py`): the
      concurrency passes scan exactly these;
    * `aux_sources` — service entry points outside the package
      (bench.py, bench_serving.py): hatch/fault-point passes include
      them;
    * `test_sources` — tests/**.py, raw text only (fault-point coverage
      and hatch references, never AST-linted);
    * `docs` — {relpath: text} for registry cross-checks
      (docs/ARCHITECTURE.md hatch table).
    """

    AUX_FILES = ("bench.py", "bench_serving.py")

    def __init__(
        self,
        sources: Sequence[Source],
        aux_sources: Sequence[Source] = (),
        test_sources: Sequence[Source] = (),
        docs: Optional[Dict[str, str]] = None,
    ):
        self.sources = list(sources)
        self.aux_sources = list(aux_sources)
        self.test_sources = list(test_sources)
        self.docs = dict(docs or {})

    # ------------------------------------------------------------ loading

    @classmethod
    def load(cls, root: str) -> "Project":
        pkg = os.path.join(root, "xllm_service_tpu")
        # The analysis package itself is excluded: its docstrings quote
        # the very patterns the text-level passes grep for (waiver
        # syntax, faults.point examples), and it owns no runtime state
        # worth concurrency-linting — linting the linter's docs is all
        # false positives.
        skip = os.path.join(pkg, "analysis") + os.sep
        sources = [
            Source(os.path.relpath(p, root), open(p, encoding="utf-8").read())
            for p in _walk_py(pkg)
            if not p.startswith(skip)
        ]
        aux = []
        for fn in cls.AUX_FILES:
            p = os.path.join(root, fn)
            if os.path.exists(p):
                aux.append(Source(fn, open(p, encoding="utf-8").read()))
        tests_dir = os.path.join(root, "tests")
        tests = []
        if os.path.isdir(tests_dir):
            tests = [
                Source(
                    os.path.relpath(p, root),
                    open(p, encoding="utf-8").read(),
                )
                for p in _walk_py(tests_dir)
            ]
        docs: Dict[str, str] = {}
        docs_dir = os.path.join(root, "docs")
        if os.path.isdir(docs_dir):
            for fn in sorted(os.listdir(docs_dir)):
                if fn.endswith(".md"):
                    p = os.path.join(docs_dir, fn)
                    docs[os.path.join("docs", fn)] = open(
                        p, encoding="utf-8"
                    ).read()
        return cls(sources, aux, tests, docs)

    @classmethod
    def from_sources(
        cls,
        sources: Dict[str, str],
        tests: Optional[Dict[str, str]] = None,
        docs: Optional[Dict[str, str]] = None,
    ) -> "Project":
        """Synthetic project for fixture-based pass unit tests."""
        return cls(
            [Source(rel, text) for rel, text in sources.items()],
            [],
            [Source(rel, text) for rel, text in (tests or {}).items()],
            docs or {},
        )

    # ----------------------------------------------------------- helpers

    def all_lintable(self) -> List[Source]:
        return self.sources + self.aux_sources

    def find(self, rel: str) -> Optional[Source]:
        for s in self.sources + self.aux_sources + self.test_sources:
            if s.rel == rel:
                return s
        return None


class LintPass:
    """One analysis. Subclasses set `id`/`title` and implement run()."""

    id: str = ""
    title: str = ""

    def run(self, project: Project) -> List[Finding]:  # pragma: no cover
        raise NotImplementedError


@dataclass
class RunResult:
    findings: List[Finding] = field(default_factory=list)
    waived: List[Finding] = field(default_factory=list)
    stale_waivers: List[Finding] = field(default_factory=list)
    checked_passes: List[str] = field(default_factory=list)

    @property
    def failed(self) -> bool:
        return bool(self.findings or self.stale_waivers)


def run_passes(
    passes: Sequence[LintPass], project: Project, check_stale_waivers: bool = True
) -> RunResult:
    """Run passes, apply waivers, flag waivers that no longer fire.

    A waiver is *used* when a finding of the named pass lands on its
    line. After all passes run, any `allow=` comment naming a pass that
    produced nothing on that line is reported as a stale waiver — the
    escape hatch can't outlive the hazard it excused. Stale-waiver
    checking only makes sense on a full run, so single-pass invocations
    (the legacy shims) disable it.
    """
    res = RunResult()
    used: Set[Tuple[str, int, str]] = set()  # (path, line, pass_id)
    known_ids = {p.id for p in passes}
    for p in passes:
        res.checked_passes.append(p.id)
        for f in p.run(project):
            src = project.find(f.path)
            allowed = src.waivers.get(f.line, set()) if src else set()
            if p.id in allowed or "*" in allowed:
                res.waived.append(f)
                used.add((f.path, f.line, p.id))
            else:
                res.findings.append(f)
    if check_stale_waivers:
        for src in project.all_lintable():
            for line, ids in src.waivers.items():
                for pid in ids:
                    if pid == "*":
                        continue
                    if pid not in known_ids:
                        res.stale_waivers.append(Finding(
                            "framework", src.rel, line,
                            f"waiver names unknown pass {pid!r}",
                        ))
                    elif (src.rel, line, pid) not in used:
                        res.stale_waivers.append(Finding(
                            "framework", src.rel, line,
                            f"stale waiver: pass {pid!r} reports nothing "
                            f"on this line — remove the allow= comment",
                        ))
    res.findings.sort(key=lambda f: (f.path, f.line, f.pass_id))
    return res


# ---------------------------------------------------------------------------
# shared AST utilities used by the concurrency passes
# ---------------------------------------------------------------------------

LOCK_FACTORIES = {"Lock", "RLock", "Condition"}

# attr-name heuristic for lock-ish context managers when the defining
# `threading.Lock()` assignment is out of view (cross-module mixins).
LOCKISH_NAME_RE = re.compile(
    r"(^|_)(lock|mu|mutex|cv|cond|sem)($|_)|(_mu|_lock|_cv)$"
)


def is_lock_factory_call(node: ast.AST) -> bool:
    """True for `threading.Lock()` / `threading.RLock()` /
    `threading.Condition(...)` (and bare `Lock()` when imported)."""
    if not isinstance(node, ast.Call):
        return False
    fn = node.func
    if isinstance(fn, ast.Attribute):
        return (
            isinstance(fn.value, ast.Name)
            and fn.value.id == "threading"
            and fn.attr in LOCK_FACTORIES
        )
    if isinstance(fn, ast.Name):
        return fn.id in LOCK_FACTORIES
    return False


def self_attr(node: ast.AST) -> Optional[str]:
    """'x' when node is `self.x`, else None."""
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return node.attr
    return None


def class_lock_attrs(cls: ast.ClassDef) -> Set[str]:
    """Self-attrs assigned a threading.Lock/RLock/Condition anywhere in
    the class body (typically __init__)."""
    locks: Set[str] = set()
    for node in ast.walk(cls):
        if isinstance(node, ast.Assign) and is_lock_factory_call(node.value):
            for t in node.targets:
                a = self_attr(t)
                if a:
                    locks.add(a)
    return locks


def class_condition_aliases(cls: ast.ClassDef) -> Dict[str, str]:
    """{cond_attr: lock_attr} for `self.X = threading.Condition(self.Y)`:
    the Condition SHARES Y, so holding X is holding Y (and X.wait()
    under Y is the canonical idiom, not a blocking call under a foreign
    lock)."""
    aliases: Dict[str, str] = {}
    for node in ast.walk(cls):
        if not (
            isinstance(node, ast.Assign)
            and isinstance(node.value, ast.Call)
        ):
            continue
        fn = node.value.func
        is_cond = (
            isinstance(fn, ast.Attribute)
            and isinstance(fn.value, ast.Name)
            and fn.value.id == "threading"
            and fn.attr == "Condition"
        ) or (isinstance(fn, ast.Name) and fn.id == "Condition")
        if not is_cond or not node.value.args:
            continue
        lock = self_attr(node.value.args[0])
        if lock is None:
            continue
        for t in node.targets:
            a = self_attr(t)
            if a:
                aliases[a] = lock
    return aliases


def with_lock_names(
    node: ast.With,
    lock_attrs: Set[str],
    aliases: Optional[Dict[str, str]] = None,
) -> Set[str]:
    """Lock attr names this `with` statement acquires: `with self.X:`
    where X is a known lock attr or matches the lock-ish heuristic.
    Acquiring a Condition that wraps a known lock counts as acquiring
    that lock too."""
    names: Set[str] = set()
    for item in node.items:
        a = self_attr(item.context_expr)
        if a and (a in lock_attrs or LOCKISH_NAME_RE.search(a)):
            names.add(a)
            if aliases and a in aliases:
                names.add(aliases[a])
    return names


def iter_functions(tree: ast.Module):
    """Yield (classname_or_None, FunctionDef) for every def in a module,
    attributing methods to their innermost class."""
    def visit(node, cls_name):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.ClassDef):
                yield from visit(child, child.name)
            elif isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield cls_name, child
                # nested defs belong to the same logical scope
                yield from visit(child, cls_name)
            else:
                yield from visit(child, cls_name)

    yield from visit(tree, None)
