"""Thread-join pass: a thread a class stores, the class must also join.

PR 6's `_campaign` join-unstarted race and two reviews' worth of
"daemon thread still running after stop()" bugs share one shape: a
`self._thread = threading.Thread(...)` that some stop/close path
forgets. A daemon thread that outlives stop() keeps mutating state the
caller believes quiesced — the flakiest bug class in the suite.

Rule (deliberately narrow so it lands clean and stays credible): every
`self.X = threading.Thread(...)` assignment in a class requires a
`self.X.join(...)` call somewhere in the same class. Fire-and-forget
threads bound to locals and worker pools collected in lists are out of
scope for the AST rule — name them in a waiver so the exception is
visible at the creation site:

    # graftlint: allow=thread-joins -- drained via self._pool.shutdown()
"""

from __future__ import annotations

import ast
from typing import List, Set

from xllm_service_tpu.analysis.core import (
    Finding,
    LintPass,
    Project,
    self_attr,
)


def _is_thread_ctor(node: ast.AST) -> bool:
    if not isinstance(node, ast.Call):
        return False
    fn = node.func
    if isinstance(fn, ast.Attribute):
        return (
            isinstance(fn.value, ast.Name)
            and fn.value.id == "threading"
            and fn.attr == "Thread"
        )
    return isinstance(fn, ast.Name) and fn.id == "Thread"


class ThreadJoinsPass(LintPass):
    id = "thread-joins"
    title = "threads stored on self but never joined"

    def run(self, project: Project) -> List[Finding]:
        findings: List[Finding] = []
        for src in project.sources:
            tree = src.tree
            if tree is None:
                continue
            for node in ast.walk(tree):
                if not isinstance(node, ast.ClassDef):
                    continue
                created = []  # (attr, lineno)
                joined: Set[str] = set()
                for sub in ast.walk(node):
                    if isinstance(sub, ast.Assign) and _is_thread_ctor(
                        sub.value
                    ):
                        for t in sub.targets:
                            a = self_attr(t)
                            if a:
                                created.append((a, sub.lineno))
                    if (
                        isinstance(sub, ast.Call)
                        and isinstance(sub.func, ast.Attribute)
                        and sub.func.attr == "join"
                    ):
                        a = self_attr(sub.func.value)
                        if a:
                            joined.add(a)
                for attr, lineno in created:
                    if attr not in joined:
                        findings.append(Finding(
                            self.id, src.rel, lineno,
                            f"{node.name}: self.{attr} is a Thread this "
                            f"class never joins — join it in the stop/"
                            f"close path (daemon threads that outlive "
                            f"stop() keep mutating 'quiesced' state) or "
                            f"waive with the drain mechanism named",
                        ))
        return findings
