"""Lock-discipline pass: guarded fields must be mutated under their lock.

Two detection modes, both scoped to one class at a time (cross-object
aliasing is out of scope — this is clang-tidy's GUARDED_BY for the 90%
case, not a whole-program alias analysis):

* **declared** — a field's initialising assignment carries

      self._waiting = deque()  # guarded by: self._lock

  and every later mutation of `self._waiting` anywhere in the class
  must sit inside `with self._lock:` (or in an exempt method — see
  below). Declaration is the preferred mode: it documents the invariant
  at the field's birthplace and survives refactors that change usage
  ratios.

* **inferred (majority-locked)** — for undeclared fields of classes
  that own at least one lock: if ≥ MIN_LOCKED_SITES mutation sites are
  under one lock and ≥ MAJORITY_FRACTION of all mutation sites agree,
  the stragglers are flagged. Catches the PR-6-style bug where one new
  call site forgets the lock the other five remembered.

A *mutation* is an assignment / augmented assignment / `del` of
`self.field` or `self.field[...]`, or a call of a mutating container
method (`append`, `pop`, `update`, ...) with `self.field` as receiver.
Reads are deliberately not checked: this codebase documents several
racy-read-by-design surfaces (engine cache snapshots, load gauges).

Exempt: `__init__`/`__del__` (no concurrent peers yet), constructor
extensions marked `# graftlint: init-only` on their `def` line (the
mixin `_init_*` convention — called only from __init__), methods whose
name ends in `_locked` (caller-holds-the-lock convention), and methods
annotated `# graftlint: holds=self._lock` on their `def` line. Holding
a Condition constructed over a known lock counts as holding that lock
(`class_condition_aliases`). Mutations
inside nested functions/lambdas are skipped — deferred execution makes
the lexically enclosing `with` meaningless.

Waive a single site with `# graftlint: allow=lock-discipline -- why`.
"""

from __future__ import annotations

import ast
from collections import Counter
from typing import Dict, List, Optional, Set, Tuple

from xllm_service_tpu.analysis.core import (
    Finding,
    GUARDED_BY_RE,
    HOLDS_RE,
    INIT_ONLY_RE,
    LintPass,
    Project,
    Source,
    class_condition_aliases,
    class_lock_attrs,
    self_attr,
    with_lock_names,
)

MUTATORS = {
    "append", "appendleft", "extend", "extendleft", "insert",
    "add", "remove", "discard", "pop", "popleft", "popitem",
    "clear", "update", "setdefault",
}

MIN_LOCKED_SITES = 3
MAJORITY_FRACTION = 0.75


class _Site:
    __slots__ = ("field", "line", "held", "method", "exempt")

    def __init__(self, field: str, line: int, held: Set[str],
                 method: str, exempt: bool):
        self.field = field
        self.line = line
        self.held = held
        self.method = method
        self.exempt = exempt


def _mutated_fields(node: ast.AST) -> List[str]:
    """Self-attr fields this single statement/expression mutates."""
    out: List[str] = []

    def target_fields(t: ast.AST) -> None:
        a = self_attr(t)
        if a:
            out.append(a)
        elif isinstance(t, ast.Subscript):
            a = self_attr(t.value)
            if a:
                out.append(a)
        elif isinstance(t, (ast.Tuple, ast.List)):
            for e in t.elts:
                target_fields(e)
        elif isinstance(t, ast.Starred):
            target_fields(t.value)

    if isinstance(node, ast.Assign):
        for t in node.targets:
            target_fields(t)
    elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
        if getattr(node, "value", True) is not None:
            target_fields(node.target)
    elif isinstance(node, ast.Delete):
        for t in node.targets:
            target_fields(t)
    elif isinstance(node, ast.Call):
        fn = node.func
        if isinstance(fn, ast.Attribute) and fn.attr in MUTATORS:
            a = self_attr(fn.value)
            if a:
                out.append(a)
    return out


class LockDisciplinePass(LintPass):
    id = "lock-discipline"
    title = "guarded fields mutated outside their guarding lock"

    def run(self, project: Project) -> List[Finding]:
        findings: List[Finding] = []
        for src in project.sources:
            tree = src.tree
            if tree is None:
                continue
            for node in ast.walk(tree):
                if isinstance(node, ast.ClassDef):
                    findings.extend(self._check_class(src, node))
        return findings

    # ------------------------------------------------------------ class

    def _check_class(self, src: Source, cls: ast.ClassDef) -> List[Finding]:
        lock_attrs = class_lock_attrs(cls)
        if not lock_attrs:
            return []
        aliases = class_condition_aliases(cls)
        declared = self._declared_guards(src, cls, lock_attrs)
        sites = self._collect_sites(src, cls, lock_attrs, aliases)
        findings: List[Finding] = []

        # declared mode
        for field, lock in declared.items():
            for s in sites:
                if s.field != field or s.exempt:
                    continue
                if lock not in s.held and "*" not in s.held:
                    findings.append(Finding(
                        self.id, src.rel, s.line,
                        f"{cls.name}.{s.method}: self.{field} is declared "
                        f"guarded by self.{lock} but is mutated here "
                        f"without holding it",
                    ))

        # inferred mode for undeclared fields
        by_field: Dict[str, List[_Site]] = {}
        for s in sites:
            if s.field in declared or s.field in lock_attrs or s.exempt:
                continue
            by_field.setdefault(s.field, []).append(s)
        for field, fsites in by_field.items():
            locked = [s for s in fsites if s.held]
            unlocked = [s for s in fsites if not s.held]
            if len(locked) < MIN_LOCKED_SITES or not unlocked:
                continue
            modal, n_modal = Counter(
                lock for s in locked for lock in sorted(s.held)[:1]
            ).most_common(1)[0]
            if n_modal / len(fsites) < MAJORITY_FRACTION:
                continue
            for s in unlocked:
                findings.append(Finding(
                    self.id, src.rel, s.line,
                    f"{cls.name}.{s.method}: self.{field} is mutated "
                    f"without self.{modal}, which guards {n_modal} of "
                    f"{len(fsites)} mutation sites (majority-locked "
                    f"inference — annotate '# guarded by: self.{modal}' "
                    f"at the field's init, fix the site, or waive)",
                ))
        return findings

    def _declared_guards(
        self, src: Source, cls: ast.ClassDef, lock_attrs: Set[str]
    ) -> Dict[str, str]:
        guards: Dict[str, str] = {}
        for node in ast.walk(cls):
            if not isinstance(node, (ast.Assign, ast.AnnAssign)):
                continue
            m = GUARDED_BY_RE.search(src.line_comment(node.lineno))
            if not m:
                continue
            targets = (
                node.targets if isinstance(node, ast.Assign)
                else [node.target]
            )
            for t in targets:
                a = self_attr(t)
                if a:
                    guards[a] = m.group(1)
        return guards

    def _collect_sites(
        self, src: Source, cls: ast.ClassDef, lock_attrs: Set[str],
        aliases: Dict[str, str],
    ) -> List[_Site]:
        sites: List[_Site] = []
        for stmt in cls.body:
            if not isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            def_line = src.line_comment(stmt.lineno)
            exempt = (
                stmt.name in ("__init__", "__del__")
                or stmt.name.endswith("_locked")
                or bool(INIT_ONLY_RE.search(def_line))
            )
            base_held: Set[str] = set()
            hm = HOLDS_RE.search(def_line)
            if hm:
                base_held.add(hm.group(1))
            self._walk(stmt, base_held, stmt.name, exempt, lock_attrs,
                       aliases, sites, top=True)
        return sites

    def _walk(
        self, node: ast.AST, held: Set[str], method: str, exempt: bool,
        lock_attrs: Set[str], aliases: Dict[str, str], sites: List[_Site],
        top: bool = False,
    ) -> None:
        if not top and isinstance(
            node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
        ):
            return  # deferred execution: enclosing `with` proves nothing
        if isinstance(node, ast.With):
            held = held | with_lock_names(node, lock_attrs, aliases)
        for field in _mutated_fields(node):
            sites.append(_Site(
                field, getattr(node, "lineno", 0), set(held), method, exempt
            ))
        for child in ast.iter_child_nodes(node):
            self._walk(child, held, method, exempt, lock_attrs, aliases,
                       sites)
