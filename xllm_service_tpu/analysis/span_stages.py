"""Span-stages pass: distributed-tracing vocabulary + plane coverage.

Two layers, mirroring the fault-points registry idiom
(docs/OBSERVABILITY.md "Distributed tracing"):

* VOCABULARY — scan the package plus the bench entry points for every
  literal stage emitted through a tracing surface
  (`RequestTracer.stage`, `SpanRing.emit`, `InstanceServer._span`,
  `engine.span_hook`, the fabric `_span_hook`s) and require it to be a
  member of the canonical vocabulary (`obs.spans.ALL_SPAN_STAGES`). A
  stage outside the vocabulary renders as an orphan track in the merged
  Perfetto timeline and silently escapes `blame_stages`' edges.

* TRACE PLANES — a registry of RPC-client call sites (one row per
  cross-process plane: dispatch, PD handoff commit, KV stream OPEN,
  fabric fetch, encoder forward, mm stream open) each of which must
  still forward the request's trace context. A refactor that drops the
  `trace` field from one plane breaks that plane's spans out of the
  assembled timeline even though nothing crashes — exactly the silent
  rot a registry row catches.
"""

from __future__ import annotations

import re
from typing import List, Optional, Sequence, Tuple

from xllm_service_tpu.analysis.core import Finding, LintPass, Project

# A stage emission: the surface call with a LITERAL second argument.
# Non-literal stages (e.g. the scheduler's `terminal` variable, whose
# values come from TERMINAL_STAGES) are the vocabulary's job to
# constrain at the definition site, not here.
EMIT_RE = re.compile(
    r"(?:\.stage|\.emit|\bspan_hook|\b_span|\b_span_hook)"
    r"\(\s*[^,()]*,\s*[\r\n ]*[\"']([a-z_]+)[\"']"
)

# Contractual trace-context forwarding sites, one row per RPC plane:
# (repo-relative file, verbatim needle, plane). The needle is the exact
# source text that puts the trace context on that plane's wire.
TRACE_PLANES: Tuple[Tuple[str, str, str], ...] = (
    ("xllm_service_tpu/api/master.py", "trace=trace_ctx",
     "master dispatch -> prefill/decode (augment_forwarded_request)"),
    ("xllm_service_tpu/api/master.py", '"trace": trace_ctx',
     "master dispatch -> legacy /encode body"),
    ("xllm_service_tpu/api/master.py", '"trace": TraceContext(',
     "master dispatch -> encoder-fabric /encode body"),
    ("xllm_service_tpu/api/instance_serving.py",
     'trace=body.get("trace")',
     "forwarded admission -> KV stream session + fabric prefetch"),
    ("xllm_service_tpu/api/instance_kv.py",
     'header["trace"] = self.trace',
     "KV stream session OPEN -> decode peer"),
    ("xllm_service_tpu/api/instance_kv.py",
     'extra["trace"] = body["trace"]',
     "PD handoff commit -> decode peer"),
    ("xllm_service_tpu/api/instance_fabric.py",
     'fetch_header["trace"] = trace',
     "prefix-fabric /kv/fetch frame -> holder"),
    ("xllm_service_tpu/api/instance_mm.py",
     'mm_open["trace"] = body["trace"]',
     "encoder /mm/open stream session -> prefill peer"),
)


class SpanStagesPass(LintPass):
    id = "span-stages"
    title = "trace-span stage vocabulary + trace-plane forwarding registry"

    def __init__(
        self,
        vocab: Optional[Sequence[str]] = None,
        planes: Optional[Sequence[Tuple[str, str, str]]] = None,
    ):
        # Injectable for fixture tests; the repo run uses the canonical
        # vocabulary and the plane registry above.
        self._vocab = vocab
        self.planes = TRACE_PLANES if planes is None else tuple(planes)

    @property
    def vocab(self) -> frozenset:
        if self._vocab is None:
            from xllm_service_tpu.obs.spans import ALL_SPAN_STAGES

            self._vocab = ALL_SPAN_STAGES
        return frozenset(self._vocab)

    def run(self, project: Project) -> List[Finding]:
        findings: List[Finding] = []
        vocab = self.vocab
        for src in project.all_lintable():
            for m in EMIT_RE.finditer(src.text):
                stage = m.group(1)
                if stage in vocab:
                    continue
                line = src.text.count("\n", 0, m.start()) + 1
                findings.append(Finding(
                    self.id, src.rel, line,
                    f"span stage {stage!r} is not in the canonical "
                    f"vocabulary (obs.spans.ALL_SPAN_STAGES) — an "
                    f"off-vocabulary stage is invisible to "
                    f"build_timeline/blame_stages",
                ))
        for rel, needle, plane in self.planes:
            src = project.find(rel)
            if src is None:
                findings.append(Finding(
                    self.id, rel, 1,
                    f"trace-plane registry names {rel} ({plane}) but the "
                    f"file is gone — update the registry row",
                ))
                continue
            if needle not in src.text:
                findings.append(Finding(
                    self.id, rel, 1,
                    f"trace plane {plane!r} no longer forwards trace "
                    f"context (needle {needle!r} missing) — spans from "
                    f"that process drop out of the assembled timeline",
                ))
        return findings
