"""Hatch-registry pass: every `XLLM_*` env hatch is documented, and
every documented hatch still exists.

Generalises the PR-9 kernel-hatch lint from `XLLM_*_KERNEL` to ALL env
hatches the serving stack reads: a hatch that never reaches
docs/ARCHITECTURE.md's table is an undocumented production switch, and
a table row whose hatch no longer exists misleads the operator reading
it. Both directions fail lint, not a reviewer's memory.

Scanned for reads: the package plus the bench entry points (bench.py /
bench_serving.py) — `os.environ.get("XLLM_...")`, `os.environ[...]`,
and `os.getenv(...)` forms. Scanned for references (the stale-row
check): any `XLLM_*` token in those sources, so a hatch mentioned in a
dispatcher table or docstring keeps its row alive. `*_KERNEL` hatches
keep the original checker's stronger rule: any token reference at all
(they reach dispatchers through helpers and name tables, not only
literal environ reads) requires a documented row.

The registry is docs/ARCHITECTURE.md: markdown table rows whose first
cell is the backticked hatch name; the LAST cell is the shipping
default and must be non-empty (a default cell of `-` fails — state the
default, that's the row's whole job).
"""

from __future__ import annotations

import re
from typing import Dict, List, Tuple

from xllm_service_tpu.analysis.core import Finding, LintPass, Project

ENV_READ_RE = re.compile(
    r"(?:environ\.get|environ\[|getenv)\(?\s*[\"'](XLLM_[A-Z0-9_]+)[\"']"
)
TOKEN_RE = re.compile(r"XLLM_[A-Z0-9_]+")
ROW_RE = re.compile(r"^\|\s*`(XLLM_[A-Z0-9_]+)`\s*\|(.+)\|\s*$")

ARCH_DOC = "docs/ARCHITECTURE.md"


def parse_hatch_table(text: str) -> Dict[str, Tuple[int, str]]:
    """{hatch: (lineno, default_cell)} from ARCHITECTURE.md table rows."""
    rows: Dict[str, Tuple[int, str]] = {}
    for i, line in enumerate(text.splitlines(), start=1):
        m = ROW_RE.match(line.strip())
        if m:
            cells = [c.strip() for c in m.group(2).split("|")]
            rows[m.group(1)] = (i, cells[-1] if cells else "")
    return rows


class HatchRegistryPass(LintPass):
    id = "hatch-registry"
    title = "XLLM_* env hatches vs the ARCHITECTURE.md hatch table"

    def run(self, project: Project) -> List[Finding]:
        findings: List[Finding] = []
        arch = project.docs.get(ARCH_DOC)
        if arch is None:
            return [Finding(
                self.id, ARCH_DOC, 1,
                "docs/ARCHITECTURE.md not found — the hatch registry "
                "has nowhere to live",
            )]
        table = parse_hatch_table(arch)
        reads: Dict[str, Tuple[str, int]] = {}  # hatch -> first read site
        referenced = set()
        for src in project.all_lintable():
            for i, line in enumerate(src.lines, start=1):
                for m in ENV_READ_RE.finditer(line):
                    reads.setdefault(m.group(1), (src.rel, i))
                referenced.update(TOKEN_RE.findall(line))
        for hatch, (rel, lineno) in sorted(reads.items()):
            if hatch not in table:
                findings.append(Finding(
                    self.id, rel, lineno,
                    f"env hatch {hatch} is read here but has no row in "
                    f"{ARCH_DOC}'s hatch table — document it with its "
                    f"shipping default",
                ))
        # Legacy check_kernel_hatches contract, kept at full strength:
        # a *_KERNEL hatch reaches dispatchers through helpers and name
        # tables, so for kernel hatches ANY token reference (not just a
        # literal environ read) requires a documented row. Report each
        # missing hatch once, at its first reference.
        reported: set = set()
        for src in project.all_lintable():
            for i, line in enumerate(src.lines, start=1):
                for tok in TOKEN_RE.findall(line):
                    if (
                        tok.endswith("_KERNEL")
                        and tok not in table
                        and tok not in reported
                    ):
                        reported.add(tok)
                        findings.append(Finding(
                            self.id, src.rel, i,
                            f"kernel hatch {tok} is referenced here but "
                            f"has no row in {ARCH_DOC}'s hatch table — "
                            f"document it with its shipping default",
                        ))
        for hatch, (lineno, default) in sorted(table.items()):
            if not default or set(default) <= {"-", " "}:
                findings.append(Finding(
                    self.id, ARCH_DOC, lineno,
                    f"{hatch}: hatch-table row has an empty Default "
                    f"cell — state the shipping default",
                ))
            if hatch not in referenced:
                findings.append(Finding(
                    self.id, ARCH_DOC, lineno,
                    f"{hatch} is documented but no longer referenced "
                    f"anywhere in the package or bench entry points — "
                    f"stale row",
                ))
        return findings
