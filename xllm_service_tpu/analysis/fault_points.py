"""Fault-points pass: injection-point hygiene for the chaos plane.

Absorbs scripts/check_fault_points.py (PR 4). Scans the package plus
bench_serving.py for every literal `faults.point("...")` site and
enforces:

* names are lowercase dotted identifiers;
* every name is UNIQUE — one injection point, one site (a duplicated
  name makes a chaos spec fire in places its author never audited);
* every name is COVERED — referenced by at least one file under
  tests/, so each recovery path the point gates is actually exercised;
* every REQUIRED point still exists — chaos specs and the
  FAULT_TOLERANCE.md tables reference these by name, so a refactor
  that silently drops one fails lint even though the generic scan
  would no longer see it.
"""

from __future__ import annotations

import re
from typing import Dict, List, Tuple

from xllm_service_tpu.analysis.core import Finding, LintPass, Project

POINT_RE = re.compile(r"faults\.point\(\s*[\r\n ]*[\"']([^\"']+)[\"']")
NAME_RE = re.compile(r"^[a-z0-9_]+(\.[a-z0-9_]+)*$")

# Contractual points — see each plane's doc for the recovery path the
# point gates (docs/FAULT_TOLERANCE.md, docs/PD_DISAGGREGATION.md,
# docs/KV_CACHE.md, docs/EPD.md).
REQUIRED_POINTS = {
    "post_json.send",
    "post_json.recv",
    "heartbeat.send",
    "fake_engine.step",
    "kv_stream.send",
    "kv_stream.recv",
    "election.keepalive",
    "store.watch",
    "reconcile.send",
    "reconcile.recv",
    "kv_fetch.send",
    "kv_fetch.recv",
    "fabric.evict_offer",
    "encode.dispatch",
    "mm_handoff.send",
    "mm_handoff.recv",
    "admission.shed",
    "fleet_sim.tick",
    "autoscale.signal",
}


class FaultPointsPass(LintPass):
    id = "fault-points"
    title = "fault-injection point uniqueness / coverage / contract"

    def run(self, project: Project) -> List[Finding]:
        findings: List[Finding] = []
        sites: List[Tuple[str, int, str]] = []  # (rel, line, name)
        for src in project.all_lintable():
            for m in POINT_RE.finditer(src.text):
                line = src.text.count("\n", 0, m.start()) + 1
                sites.append((src.rel, line, m.group(1)))
        if not sites:
            return [Finding(
                self.id, "xllm_service_tpu", 1,
                "no faults.point(...) call sites found at all",
            )]
        by_name: Dict[str, List[Tuple[str, int]]] = {}
        for rel, line, name in sites:
            if not NAME_RE.match(name):
                findings.append(Finding(
                    self.id, rel, line, f"bad point name {name!r}",
                ))
            by_name.setdefault(name, []).append((rel, line))
        for name, where in sorted(by_name.items()):
            if len(where) > 1:
                for rel, line in where:
                    findings.append(Finding(
                        self.id, rel, line,
                        f"point {name!r} defined at {len(where)} sites: "
                        + ", ".join(f"{r}:{l}" for r, l in where),
                    ))
        first = next(iter(project.all_lintable()))
        for name in sorted(REQUIRED_POINTS - set(by_name)):
            findings.append(Finding(
                self.id, first.rel, 1,
                f"required point {name!r} has no faults.point call site",
            ))
        test_blob = "\n".join(s.text for s in project.test_sources)
        for name in sorted(by_name):
            if name not in test_blob:
                rel, line = by_name[name][0]
                findings.append(Finding(
                    self.id, rel, line,
                    f"point {name!r} is not referenced by any test "
                    f"under tests/",
                ))
        return findings
