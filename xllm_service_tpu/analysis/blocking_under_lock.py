"""Blocking-call-under-lock pass: no RPC / sleep / queue-wait while a
lock is held.

Every recent latency cliff and near-deadlock in review traced to the
same shape: a `with self._mu:` block that grew an RPC or a sleep. A
blocking call under a lock turns one slow peer into a fleet-wide stall
(every thread contending that lock queues behind the socket), and a
lock held across a blocking call is half of every real deadlock cycle.

What counts as blocking (curated — precision over recall, the lint
must land clean and stay credible):

* our own RPC plane: `post_json`, `post_json_retrying`, `post_bytes`,
  `post_bytes_raw`, `urlopen`, `create_connection`;
* jax dispatch/transfer sync points: `block_until_ready`,
  `device_put`, `device_get`;
* raw sockets: `.recv`, `.recv_into`, `.sendall`, `.accept`,
  `.connect`;
* `time.sleep` (and a bare imported `sleep`);
* subprocess: `run`, `check_output`, `check_call`, `communicate`;
* `.wait` / `.wait_for` — EXCEPT the Condition self-wait idiom
  (`with self._cv: self._cv.wait()` releases the lock it waits on);
* `.join` on thread-ish receivers (terminal name containing `thread`,
  `worker`, or a bare `t`/`th` local) — `str.join`/`os.path.join` are
  not flagged;
* `.put` / `.get` on queue-ish receivers (terminal name ending in
  `queue`/`_q`/`q`) without `block=False`/`timeout=0` —
  `put_nowait`/`get_nowait` never match.

Held-lock detection mirrors the lock-discipline pass: `with self.X:`
where X is a class lock attr or lock-ish by name, `with <module_lock>:`
for module-level locks, plus `# graftlint: holds=self._lock` method
annotations (a caller-holds contract means the body IS under the lock).

Waive a justified site with
`# graftlint: allow=blocking-under-lock -- why`.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Set, Tuple

from xllm_service_tpu.analysis.core import (
    Finding,
    HOLDS_RE,
    LOCKISH_NAME_RE,
    LintPass,
    Project,
    Source,
    class_condition_aliases,
    class_lock_attrs,
    is_lock_factory_call,
    self_attr,
)

BLOCKING_FUNCS = {
    "post_json", "post_json_retrying", "post_bytes", "post_bytes_raw",
    "urlopen", "create_connection",
    "check_output", "check_call", "communicate",
    # jax dispatch/transfer: device sync under a service lock turns one
    # slow step into a fleet-wide stall
    "block_until_ready", "device_put", "device_get",
}
SOCKET_METHODS = {"recv", "recv_into", "sendall", "accept", "connect"}
THREADISH_RE = re.compile(r"(thread|worker|sender)s?\d*$|^(t|th|thr)\d*$")
QUEUEISH_RE = re.compile(r"(queue|_q)$|^q\d*$")


def _terminal_name(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def _is_nonblocking_kwargs(call: ast.Call) -> bool:
    for kw in call.keywords:
        if kw.arg == "block" and isinstance(kw.value, ast.Constant) \
                and kw.value.value is False:
            return True
        if kw.arg == "timeout" and isinstance(kw.value, ast.Constant) \
                and kw.value.value == 0:
            return True
    return False


class BlockingUnderLockPass(LintPass):
    id = "blocking-under-lock"
    title = "blocking calls made while holding a lock"

    def run(self, project: Project) -> List[Finding]:
        findings: List[Finding] = []
        for src in project.sources:
            tree = src.tree
            if tree is None:
                continue
            module_locks = {
                t.id
                for node in tree.body
                if isinstance(node, ast.Assign)
                and is_lock_factory_call(node.value)
                for t in node.targets
                if isinstance(t, ast.Name)
            }
            for node in ast.walk(tree):
                if isinstance(node, ast.ClassDef):
                    lock_attrs = class_lock_attrs(node)
                    aliases = class_condition_aliases(node)
                    for stmt in node.body:
                        if isinstance(
                            stmt, (ast.FunctionDef, ast.AsyncFunctionDef)
                        ):
                            self._walk_fn(
                                src, node.name, stmt, lock_attrs, aliases,
                                module_locks, findings,
                            )
            for stmt in tree.body:
                if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    self._walk_fn(
                        src, None, stmt, set(), {}, module_locks, findings
                    )
        return findings

    # -------------------------------------------------------------- walk

    def _walk_fn(
        self,
        src: Source,
        cls_name: Optional[str],
        fn: ast.AST,
        lock_attrs: Set[str],
        aliases: Dict[str, str],
        module_locks: Set[str],
        findings: List[Finding],
    ) -> None:
        base_held: Dict[str, str] = {}  # lock label -> ast dump of expr
        hm = HOLDS_RE.search(src.line_comment(fn.lineno))
        if hm:
            base_held[f"self.{hm.group(1)}"] = ast.dump(
                ast.parse(f"self.{hm.group(1)}", mode="eval").body
            )

        def walk(node: ast.AST, held: Dict[str, str], top: bool) -> None:
            if not top and isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
            ):
                return  # deferred body: not executed under this lock
            if isinstance(node, ast.With):
                add: Dict[str, str] = {}
                for item in node.items:
                    expr = item.context_expr
                    a = self_attr(expr)
                    if a and (a in lock_attrs or LOCKISH_NAME_RE.search(a)):
                        add[f"self.{a}"] = ast.dump(expr)
                        if a in aliases:
                            # `with self._cv:` acquires the lock the
                            # Condition wraps.
                            add[f"self.{aliases[a]}"] = ast.dump(
                                ast.parse(
                                    f"self.{aliases[a]}", mode="eval"
                                ).body
                            )
                    elif isinstance(expr, ast.Name) and (
                        expr.id in module_locks
                        or LOCKISH_NAME_RE.search(expr.id)
                    ):
                        add[expr.id] = ast.dump(expr)
                if add:
                    held = {**held, **add}
            if isinstance(node, ast.Call) and held:
                msg = self._classify(node, held, aliases)
                if msg:
                    where = f"{cls_name}." if cls_name else ""
                    findings.append(Finding(
                        self.id, src.rel, node.lineno,
                        f"{where}{getattr(fn, 'name', '?')}: {msg} while "
                        f"holding {', '.join(sorted(held))} — move it "
                        f"outside the lock or waive",
                    ))
            for child in ast.iter_child_nodes(node):
                walk(child, held, False)

        walk(fn, base_held, True)

    # ---------------------------------------------------------- classify

    def _classify(
        self, call: ast.Call, held: Dict[str, str],
        aliases: Dict[str, str],
    ) -> Optional[str]:
        fn = call.func
        name = _terminal_name(fn)
        if name is None:
            return None
        # our RPC plane / subprocess / dns
        if name in BLOCKING_FUNCS:
            return f"blocking call {name}()"
        # time.sleep / bare sleep
        if name == "sleep":
            if isinstance(fn, ast.Attribute):
                if not (
                    isinstance(fn.value, ast.Name) and fn.value.id == "time"
                ):
                    return None
            return "time.sleep()"
        if not isinstance(fn, ast.Attribute):
            return None
        recv = fn.value
        recv_name = _terminal_name(recv) or ""
        if name in SOCKET_METHODS:
            return f"socket .{name}()"
        if name in ("wait", "wait_for"):
            # Condition self-wait releases the lock it waits on — both
            # `with self._cv: self._cv.wait()` and the shared-lock form
            # `self._cv = Condition(self._mu); with self._mu: _cv.wait()`.
            if ast.dump(recv) in held.values():
                return None
            a = self_attr(recv)
            if a and a in aliases and f"self.{aliases[a]}" in held:
                return None
            return f".{name}() on {recv_name or 'an object'}"
        if name == "join":
            if THREADISH_RE.search(recv_name):
                return f"thread .join() on {recv_name}"
            return None
        if name in ("put", "get"):
            if QUEUEISH_RE.search(recv_name) and not _is_nonblocking_kwargs(
                call
            ):
                return f"queue .{name}() on {recv_name}"
            return None
        return None
