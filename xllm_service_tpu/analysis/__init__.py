"""graftlint: project-wide concurrency + registry static analysis.

One framework, pluggable passes, single runner (`scripts/graftlint.py
--all`), enforced repo-wide as a tier-1 test (tests/test_graftlint.py).
docs/STATIC_ANALYSIS.md is the pass catalog + annotation/waiver syntax;
`xllm_service_tpu/obs/locktrace.py` is the runtime half (lock-order
sanitizer for the chaos suites).
"""

from xllm_service_tpu.analysis.core import (
    Finding,
    LintPass,
    Project,
    RunResult,
    Source,
    run_passes,
)
from xllm_service_tpu.analysis.blocking_under_lock import BlockingUnderLockPass
from xllm_service_tpu.analysis.fault_points import (
    REQUIRED_POINTS,
    FaultPointsPass,
)
from xllm_service_tpu.analysis.hatch_registry import HatchRegistryPass
from xllm_service_tpu.analysis.lock_discipline import LockDisciplinePass
from xllm_service_tpu.analysis.metric_names import MetricNamesPass
from xllm_service_tpu.analysis.sharding_rules import ShardingRulesPass
from xllm_service_tpu.analysis.span_stages import TRACE_PLANES, SpanStagesPass
from xllm_service_tpu.analysis.thread_joins import ThreadJoinsPass
from xllm_service_tpu.analysis.thread_ownership import ThreadOwnershipPass


def all_passes(runtime: bool = True):
    """The canonical pass list, in catalog order (docs/STATIC_ANALYSIS.md).

    `runtime=False` skips probes that import live components (the
    metric-names exposition render) — used by fixture unit tests.
    """
    return [
        LockDisciplinePass(),
        BlockingUnderLockPass(),
        ThreadOwnershipPass(),
        ThreadJoinsPass(),
        HatchRegistryPass(),
        ShardingRulesPass(),
        MetricNamesPass(runtime=runtime),
        FaultPointsPass(),
        SpanStagesPass(),
    ]


__all__ = [
    "Finding",
    "LintPass",
    "Project",
    "RunResult",
    "Source",
    "run_passes",
    "all_passes",
    "REQUIRED_POINTS",
    "TRACE_PLANES",
    "BlockingUnderLockPass",
    "FaultPointsPass",
    "HatchRegistryPass",
    "LockDisciplinePass",
    "MetricNamesPass",
    "ShardingRulesPass",
    "SpanStagesPass",
    "ThreadJoinsPass",
    "ThreadOwnershipPass",
]
