"""Metric-names pass: naming conventions for every registered metric.

Absorbs scripts/check_metric_names.py (PR 2) into the framework. Two
layers:

* STATIC — scan the package for every name registered through a
  MetricsRegistry factory (`.counter("...")`/`.gauge(`/`.histogram(`)
  and for hand-written `# TYPE` exposition lines, then enforce what the
  registry asserts at runtime: `^xllm_[a-z0-9_]+$`, counters end in
  `_total`, gauges/histograms don't, histogram base names never use the
  render-reserved `_bucket`/`_sum`/`_count` suffixes. The scan catches
  names on code paths tests never execute.

* RUNTIME (optional, default on for repo runs) — render one
  Counter/Gauge/Histogram through a real registry and assert the
  exposition contract (single TYPE line per family, cumulative +Inf
  bucket, `_sum`/`_count` series). Fixture-driven unit tests construct
  the pass with `runtime=False`.
"""

from __future__ import annotations

import re
from typing import List

from xllm_service_tpu.analysis.core import Finding, LintPass, Project

NAME_RE = re.compile(r"^xllm_[a-z0-9_]+$")
REG_RE = re.compile(
    r"\.(counter|gauge|histogram)\(\s*[\r\n ]*[\"']([A-Za-z0-9_]+)[\"']"
)
TYPE_LINE_RE = re.compile(r"#\s*TYPE\s+([A-Za-z0-9_]+)\s+(\w+)")


class MetricNamesPass(LintPass):
    id = "metric-names"
    title = "metric naming conventions + exposition contract"

    def __init__(self, runtime: bool = True):
        self.runtime = runtime

    def run(self, project: Project) -> List[Finding]:
        findings: List[Finding] = []
        n = 0
        for src in project.sources:
            regs = [
                (m.group(1), m.group(2),
                 src.text.count("\n", 0, m.start()) + 1)
                for m in REG_RE.finditer(src.text)
            ]
            regs += [
                (kind, name, src.text.count("\n", 0, m.start()) + 1)
                for m in TYPE_LINE_RE.finditer(src.text)
                for name, kind in [(m.group(1), m.group(2))]
                if kind in ("counter", "gauge", "histogram")
            ]
            for kind, name, line in regs:
                n += 1
                where = f"{kind} {name!r}"
                if not NAME_RE.match(name):
                    findings.append(Finding(
                        self.id, src.rel, line,
                        f"{where}: must match {NAME_RE.pattern}",
                    ))
                    continue
                if kind == "counter" and not name.endswith("_total"):
                    findings.append(Finding(
                        self.id, src.rel, line,
                        f"{where}: counters must end in _total",
                    ))
                if kind in ("gauge", "histogram") and name.endswith("_total"):
                    findings.append(Finding(
                        self.id, src.rel, line,
                        f"{where}: only counters may end in _total",
                    ))
                if kind == "histogram" and any(
                    name.endswith(s) for s in ("_bucket", "_sum", "_count")
                ):
                    findings.append(Finding(
                        self.id, src.rel, line,
                        f"{where}: histogram base name uses a "
                        f"render-reserved suffix",
                    ))
        if self.runtime:
            findings.extend(self._runtime_probe())
        return findings

    def _runtime_probe(self) -> List[Finding]:
        from xllm_service_tpu.obs import MetricsRegistry

        errs: List[Finding] = []
        reg = MetricsRegistry()
        reg.counter("xllm_lint_probe_total", "probe").inc(2)
        reg.gauge("xllm_lint_probe_depth", "probe").set(3)
        h = reg.histogram("xllm_lint_probe_ms", "probe", buckets=(1.0, 10.0))
        h.observe(0.5)
        h.observe(5.0)
        h.observe(50.0)
        text = reg.render()
        for fam in ("xllm_lint_probe_total", "xllm_lint_probe_depth",
                    "xllm_lint_probe_ms"):
            c = text.count(f"# TYPE {fam} ")
            if c != 1:
                errs.append(Finding(
                    self.id, "xllm_service_tpu/obs/metrics.py", 1,
                    f"render: {c} TYPE lines for {fam} (want 1)",
                ))
        for needle in (
            'xllm_lint_probe_ms_bucket{le="1"} 1',
            'xllm_lint_probe_ms_bucket{le="10"} 2',
            'xllm_lint_probe_ms_bucket{le="+Inf"} 3',
            "xllm_lint_probe_ms_sum 55.5",
            "xllm_lint_probe_ms_count 3",
        ):
            if needle not in text:
                errs.append(Finding(
                    self.id, "xllm_service_tpu/obs/metrics.py", 1,
                    f"render: missing sample {needle!r}",
                ))
        return errs
