"""Thread-ownership pass: `@thread_owned` surfaces are only called from
owning-thread code.

`common/concurrency.py` gives hot single-threaded state a contract:
methods decorated `@thread_owned("engine")` may only run on the thread
that called `claim_thread(self, "engine")` (the engine loop claims at
`_loop` entry, releases on exit). The decorator runtime-asserts under
`XLLM_THREAD_CHECKS=1` (on for the test suite); this pass is the static
half — it checks *call sites* so a violation fails lint before a racy
test has to catch it.

Static rule, scoped per class (receiver must be `self` — cross-object
calls are covered by the runtime assert):

    a call `self.m(...)` where `m` is @thread_owned in this class must
    appear inside a method that is itself @thread_owned (same realm) or
    a *claimer* (a method that calls `claim_thread`).

The closure this forces is the point: decorating `_slot_admit` makes
every caller prove it is on the engine thread too, so the engine-thread
call chain is marked end to end and a new off-thread call site fails CI
instead of corrupting slot state.

Waive a deliberate exception with
`# graftlint: allow=thread-ownership -- why`.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from xllm_service_tpu.analysis.core import (
    Finding,
    LintPass,
    Project,
    Source,
    self_attr,
)


def _decorator_realm(dec: ast.AST) -> Optional[str]:
    """Realm string when `dec` is a thread_owned decoration."""
    if isinstance(dec, ast.Call):
        name = dec.func
        tag = name.attr if isinstance(name, ast.Attribute) else (
            name.id if isinstance(name, ast.Name) else None
        )
        if tag == "thread_owned":
            if dec.args and isinstance(dec.args[0], ast.Constant):
                return str(dec.args[0].value)
            return "?"
    return None


def _is_claimer(fn: ast.AST) -> bool:
    for node in ast.walk(fn):
        if isinstance(node, ast.Call):
            f = node.func
            tag = f.attr if isinstance(f, ast.Attribute) else (
                f.id if isinstance(f, ast.Name) else None
            )
            if tag == "claim_thread":
                return True
    return False


class ThreadOwnershipPass(LintPass):
    id = "thread-ownership"
    title = "@thread_owned methods called from unowned code"

    def run(self, project: Project) -> List[Finding]:
        findings: List[Finding] = []
        for src in project.sources:
            tree = src.tree
            if tree is None:
                continue
            for node in ast.walk(tree):
                if isinstance(node, ast.ClassDef):
                    findings.extend(self._check_class(src, node))
        return findings

    def _check_class(self, src: Source, cls: ast.ClassDef) -> List[Finding]:
        owned: Dict[str, str] = {}  # method -> realm
        methods: List[ast.FunctionDef] = [
            s for s in cls.body
            if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef))
        ]
        for m in methods:
            for dec in m.decorator_list:
                realm = _decorator_realm(dec)
                if realm:
                    owned[m.name] = realm
        if not owned:
            return []
        findings: List[Finding] = []
        for m in methods:
            caller_realms = {
                _decorator_realm(d) for d in m.decorator_list
            } - {None}
            claimer = _is_claimer(m)

            def visit(node: ast.AST, covered: bool) -> None:
                for child in ast.iter_child_nodes(node):
                    if isinstance(
                        child,
                        (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda),
                    ):
                        # a nested def runs on whatever thread calls it
                        # later; its body can't inherit this method's
                        # ownership — owned calls inside it are flagged.
                        visit(child, False)
                        continue
                    if isinstance(child, ast.Call):
                        attr = self_attr(child.func)
                        if attr is not None and attr in owned:
                            realm = owned[attr]
                            ok = covered and (
                                realm in caller_realms or claimer
                            )
                            if not ok:
                                findings.append(Finding(
                                    self.id, src.rel, child.lineno,
                                    f"{cls.name}.{m.name} calls "
                                    f"self.{attr}() which is "
                                    f"@thread_owned({realm!r}), but "
                                    f"{m.name} is neither "
                                    f"@thread_owned({realm!r}) nor a "
                                    f"claim_thread() claimer — an "
                                    f"off-{realm}-thread call would "
                                    f"corrupt {realm}-owned state",
                                ))
                    visit(child, covered)

            visit(m, True)
        return findings
