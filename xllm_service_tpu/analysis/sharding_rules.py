"""Sharding-rule pass: every param leaf the model families create gets a
partition rule.

The GSPMD tier (docs/SHARDING.md) only works when EVERY leaf of the
model pytree carries a NamedSharding from
`parallel/sharding.param_shardings` — a leaf added to a family's
`init_params` without a matching rule is silently REPLICATED across the
mesh by the jit default, which "works" on the virtual test mesh and then
multiplies HBM residency by tp on a real pod (a 70B wq replicated 8x is
an instant OOM). The runtime half of this guarantee is the
tests/test_sharding_rules.py structure matrix (jax.eval_shape over every
registered family × mesh); this pass is the static tripwire that fires
on the PR that ADDS the leaf, before any test constructs that family on
a mesh.

Mechanics: collect every string key assigned into the param tree by the
model modules' `init_params` functions (dict literals, `d["k"] = ...`,
`d.update({...})` — the only forms the families use) AND by any
module-local helper init_params calls, transitively — deepseek's
`_layer_stack` builds the whole per-layer leaf dict (attention + the
MoE expert/router/shared-expert leaves) out of line, and a pass that
stopped at the init_params body would wave through exactly the
expert-axis leaves the EP tier must shard (ISSUE 15). Then require
model-keys ⊆ the keys `param_shardings` assigns a spec for in
parallel/sharding.py. Keys that are runtime-installed with explicit
shardings (the multi-LoRA `lora_<proj>_{a,b}` stacks from
set_lora_adapters) are exempt by prefix.

Second rule (ISSUE 18): every `lax.ppermute` axis name must be one the
meshes actually carry. A ppermute over a misspelled axis isn't a
compile error at the call site — it surfaces as an unbound-axis failure
only when the shard_map finally runs on a mesh, which on the overlap
paths (ops/collective_matmul.py) happens only with the hatch ON and
tp>1, i.e. never in a hatch-off CI lane. The pass resolves the axis
argument statically (string literal, a parameter default, or a simple
local/closure `name = "lit"` assignment) and flags any resolved name
outside the mesh vocabulary; an unresolvable dynamic axis is skipped,
not guessed.
"""

from __future__ import annotations

import ast
from typing import List, Set

from xllm_service_tpu.analysis.core import Finding, LintPass, Project

MODEL_FILES = (
    "xllm_service_tpu/models/llama.py",
    "xllm_service_tpu/models/deepseek.py",
)
RULES_FILE = "xllm_service_tpu/parallel/sharding.py"

# Installed at runtime with an explicit sharding, never by init_params.
EXEMPT_PREFIXES = ("lora_",)

# The mesh axis vocabulary: parallel/mesh.py build_mesh creates
# dp/sp/ep/tp; parallel/pipeline.py's GPipe tier runs over a
# caller-built `pp` axis. A ppermute naming anything else can never
# bind on a serving mesh.
MESH_AXES = frozenset({"dp", "tp", "ep", "sp", "pp"})


def _str_keys_of_dict(node: ast.AST) -> List[str]:
    if isinstance(node, ast.Dict):
        return [
            k.value
            for k in node.keys
            if isinstance(k, ast.Constant) and isinstance(k.value, str)
        ]
    return []


def _collect_assigned_keys(fn: ast.AST) -> Set[str]:
    """String keys assigned into any dict within one function body:
    dict literals, `d["k"] = ...` subscript stores, and
    `d.update({...})` calls."""
    keys: Set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Dict):
            keys.update(_str_keys_of_dict(node))
        elif isinstance(node, ast.Assign):
            for tgt in node.targets:
                if (
                    isinstance(tgt, ast.Subscript)
                    and isinstance(tgt.slice, ast.Constant)
                    and isinstance(tgt.slice.value, str)
                ):
                    keys.add(tgt.slice.value)
        elif (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == "update"
        ):
            for arg in node.args:
                keys.update(_str_keys_of_dict(arg))
    return keys


def _functions(tree: ast.Module, name: str) -> List[ast.AST]:
    return [
        n
        for n in ast.walk(tree)
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        and n.name == name
    ]


def _module_functions(tree: ast.Module):
    return {
        n.name: n
        for n in ast.walk(tree)
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
    }


def _collect_keys_transitive(tree: ast.Module, root: ast.AST) -> Set[str]:
    """Keys assigned by `root` plus every module-local function it calls
    (transitively): init_params delegating its leaf dict to a helper
    (_layer_stack) must not hide leaves from the pass."""
    fns = _module_functions(tree)
    keys: Set[str] = set()
    seen: Set[str] = set()
    stack = [root]
    while stack:
        fn = stack.pop()
        keys |= _collect_assigned_keys(fn)
        for node in ast.walk(fn):
            if isinstance(node, ast.Call) and isinstance(
                node.func, ast.Name
            ):
                callee = node.func.id
                if callee in fns and callee not in seen:
                    seen.add(callee)
                    stack.append(fns[callee])
    return keys


_FN_NODES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)


def _own_nodes(root: ast.AST):
    """Walk `root` without descending into nested function bodies, so a
    call binds to its INNERMOST scope's environment, not an outer one."""
    stack = list(ast.iter_child_nodes(root))
    while stack:
        node = stack.pop()
        yield node
        if not isinstance(node, _FN_NODES):
            stack.extend(ast.iter_child_nodes(node))


def _scope_env(fn: ast.AST, inherited: dict) -> dict:
    """{name: string value} visible inside `fn`: closure bindings, then
    parameter defaults (`axis: str = "tp"`), then simple local
    `name = "lit"` assignments. Non-string rebinds shadow to None."""
    env = dict(inherited)
    if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
        a = fn.args
        pos = a.posonlyargs + a.args
        for arg, dflt in zip(pos[len(pos) - len(a.defaults):], a.defaults):
            if isinstance(dflt, ast.Constant) and isinstance(dflt.value, str):
                env[arg.arg] = dflt.value
        for arg, dflt in zip(a.kwonlyargs, a.kw_defaults):
            if (
                dflt is not None
                and isinstance(dflt, ast.Constant)
                and isinstance(dflt.value, str)
            ):
                env[arg.arg] = dflt.value
    for node in _own_nodes(fn):
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            tgt = node.targets[0]
            if isinstance(tgt, ast.Name):
                if isinstance(node.value, ast.Constant) and isinstance(
                    node.value.value, str
                ):
                    env[tgt.id] = node.value.value
                else:
                    env[tgt.id] = None  # dynamic rebind: unresolvable
    return env


def _ppermute_axis_arg(call: ast.Call):
    """The axis argument node of a `*.ppermute(x, axis_name, perm)`
    call, or None when the call shape doesn't match."""
    if not (
        isinstance(call.func, ast.Attribute)
        and call.func.attr == "ppermute"
    ):
        return None
    if len(call.args) >= 2:
        return call.args[1]
    for kw in call.keywords:
        if kw.arg == "axis_name":
            return kw.value
    return None


def _ppermute_findings(src, pass_id: str) -> List[Finding]:
    findings: List[Finding] = []

    def visit(scope: ast.AST, inherited: dict) -> None:
        env = _scope_env(scope, inherited)
        for node in _own_nodes(scope):
            if isinstance(node, ast.Call):
                axis_node = _ppermute_axis_arg(node)
                if axis_node is None:
                    continue
                axis = None
                if isinstance(axis_node, ast.Constant) and isinstance(
                    axis_node.value, str
                ):
                    axis = axis_node.value
                elif isinstance(axis_node, ast.Name):
                    axis = env.get(axis_node.id)
                if axis is not None and axis not in MESH_AXES:
                    findings.append(Finding(
                        pass_id, src.rel, node.lineno,
                        f"ppermute over axis {axis!r}, which no mesh "
                        f"carries (axes: "
                        f"{', '.join(sorted(MESH_AXES))}) — the ring "
                        f"would fail to bind the moment the shard_map "
                        f"runs on a real mesh (parallel/mesh.py)",
                    ))
            if isinstance(node, _FN_NODES):
                visit(node, env)

    if src.tree is not None:
        visit(src.tree, {})
    return findings


class ShardingRulesPass(LintPass):
    id = "sharding-rules"
    title = "model param leaves vs parallel/sharding.py partition rules"

    def run(self, project: Project) -> List[Finding]:
        findings: List[Finding] = []
        rules_src = None
        model_srcs = []
        for src in project.sources:
            if src.rel == RULES_FILE:
                rules_src = src
            elif src.rel in MODEL_FILES:
                model_srcs.append(src)
            # Axis-vocabulary rule runs on every package source — the
            # rings live in ops/, parallel/, and the model families.
            findings.extend(_ppermute_findings(src, self.id))
        if rules_src is None or rules_src.tree is None:
            return findings + [Finding(
                self.id, RULES_FILE, 1,
                "parallel/sharding.py not found/parsable — the partition "
                "rules have nowhere to live",
            )]
        rule_keys: Set[str] = set()
        for fn in _functions(rules_src.tree, "param_shardings"):
            rule_keys |= _collect_assigned_keys(fn)
        if not rule_keys:
            return findings + [Finding(
                self.id, RULES_FILE, 1,
                "param_shardings assigns no rule keys — the pass cannot "
                "cross-check the model tree",
            )]
        for src in model_srcs:
            if src.tree is None:
                continue
            for fn in _functions(src.tree, "init_params"):
                for key in sorted(_collect_keys_transitive(src.tree, fn)):
                    if key in rule_keys:
                        continue
                    if any(key.startswith(p) for p in EXEMPT_PREFIXES):
                        continue
                    findings.append(Finding(
                        self.id, src.rel, fn.lineno,
                        f"param leaf {key!r} is created by init_params "
                        f"but has no rule in param_shardings "
                        f"({RULES_FILE}) — it would silently replicate "
                        f"across every mesh shard; add a NamedSharding "
                        f"rule (docs/SHARDING.md)",
                    ))
        return findings
