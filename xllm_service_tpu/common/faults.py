"""Deterministic fault injection for the service/control plane.

P/D-Serve (arXiv:2408.08147) and the xLLM technical report
(arXiv:2510.14686) both treat fast failure detection and transparent
retry as first-class service duties — which means the recovery paths
need to be *exercised reproducibly*, not just written. This module is
the single switchboard: production code marks named injection points
with `faults.point(<name>, **ctx)` (a no-op unless a plan is
installed), and tests / `bench_serving.py --chaos-spec` install a
seeded `FaultPlan` that decides — deterministically — which hits
drop, delay, error, or partition.

Point names are string literals at their call sites; uniqueness and
test coverage are linted by `scripts/check_fault_points.py` (wired
next to `check_metric_names.py`). The data plane exposes
`post_json.send/recv`, `heartbeat.send`, `fake_engine.step`,
`kv_stream.send/recv`, the prefix-fabric points
`kv_fetch.send/recv` (chaos must degrade to recompute, never error —
docs/KV_CACHE.md) and `fabric.evict_offer` (chaos = the block dies
locally), and the encoder-fabric points `encode.dispatch` (chaos =
master re-routes to another encoder) and `mm_handoff.send/recv` (chaos
must degrade to the monolithic /mm/import push, never error —
docs/EPD.md); the control plane `election.keepalive` (drop = fast
demote, delay past the lease TTL = the split-brain window),
`store.watch`, and `reconcile.send/recv` — the docs/FAULT_TOLERANCE.md
tables map each to its recovery path.

Plan spec (JSON, via `install_spec`, `--chaos-spec`, or the
`XLLM_CHAOS_SPEC` env var read at first use):

    {"seed": 0,
     "rules": [
       {"point": "post_json.send",   # exact injection-point name
        "match": "127.0.0.1:9999",   # substring over the ctx values
        "action": "error",           # drop | delay | error | partition
        "prob": 1.0,                 # seeded Bernoulli per hit
        "after": 3,                  # skip the first N matching hits
        "count": 2,                  # fire at most N times (0 = forever)
        "delay_ms": 50}]}            # action=delay sleep

Actions, as seen by the call site:
  * drop      — raise FaultInjected (the operation never happens);
  * error     — raise FaultInjected tagged `sent=True` (the operation
                may or may not have happened: the indeterminate case);
  * partition — alias of drop, conventionally matched on an address /
                instance name so both directions of a link fail;
  * delay     — time.sleep(delay_ms) then proceed normally.

Determinism: each rule owns a `random.Random(seed ^ crc(point|idx))`
stream and its own hit/fire counters, so a plan replayed against the
same call sequence injects at exactly the same hits. Concurrency can
reorder *which thread* sees a given hit; specs that need per-instance
determinism should match on the instance/address in ctx.
"""

from __future__ import annotations

import json
import threading
import time
import zlib
from dataclasses import dataclass, field
from random import Random
from typing import Any, Dict, List, Optional

__all__ = [
    "FaultInjected",
    "FaultRule",
    "FaultPlan",
    "point",
    "install_plan",
    "install_spec",
    "clear",
    "get_plan",
    "set_point_observer",
]


class FaultInjected(ConnectionError):
    """Raised at an injection point for drop/error/partition actions.

    Subclasses ConnectionError so existing except-paths treat it like
    the network failure it simulates. `sent` mirrors the http_utils
    retry contract: False = the operation definitely never happened
    (safe to retry), True = indeterminate.
    """

    def __init__(self, point_name: str, action: str, sent: bool = False):
        super().__init__(f"injected {action} at {point_name}")
        self.point_name = point_name
        self.action = action
        self.sent = sent


_ACTIONS = ("drop", "delay", "error", "partition")


@dataclass
class FaultRule:
    point: str
    action: str = "drop"
    match: str = ""
    prob: float = 1.0
    after: int = 0
    count: int = 0  # 0 = unlimited
    delay_ms: float = 0.0
    # runtime state (not part of the spec)
    hits: int = 0
    fired: int = 0
    _rng: Optional[Random] = field(default=None, repr=False)

    def __post_init__(self) -> None:
        if self.action not in _ACTIONS:
            raise ValueError(
                f"fault action {self.action!r} not in {_ACTIONS}"
            )

    def seed_rng(self, seed: int, idx: int) -> None:
        tag = zlib.crc32(f"{self.point}|{idx}".encode())
        self._rng = Random((seed ^ tag) & 0xFFFFFFFF)

    def matches(self, name: str, ctx: Dict[str, Any]) -> bool:
        if name != self.point:
            return False
        if not self.match:
            return True
        return any(self.match in str(v) for v in ctx.values())

    def decide(self) -> bool:
        """One matching hit: fire or not (mutates counters)."""
        self.hits += 1
        if self.hits <= self.after:
            return False
        if self.count and self.fired >= self.count:
            return False
        if self.prob < 1.0:
            rng = self._rng or Random(0)
            if rng.random() >= self.prob:
                return False
        self.fired += 1
        return True


class FaultPlan:
    """A seeded set of rules; thread-safe; installable process-wide."""

    def __init__(self, seed: int = 0, rules: Optional[List[FaultRule]] = None):
        self.seed = int(seed)
        self._mu = threading.Lock()
        self._rules: List[FaultRule] = []
        for r in rules or []:
            self.add_rule(r)

    @classmethod
    def from_spec(cls, spec) -> "FaultPlan":
        """Build from a dict, a JSON string, or an `@path` JSON file."""
        if isinstance(spec, str):
            if spec.startswith("@"):
                with open(spec[1:]) as f:
                    spec = json.load(f)
            else:
                spec = json.loads(spec)
        if not isinstance(spec, dict):
            raise ValueError("fault spec must be a JSON object")
        plan = cls(seed=int(spec.get("seed", 0)))
        for j in spec.get("rules", []):
            plan.add_rule(FaultRule(**{
                k: j[k]
                for k in ("point", "action", "match", "prob", "after",
                          "count", "delay_ms")
                if k in j
            }))
        return plan

    def add_rule(self, rule: FaultRule) -> FaultRule:
        with self._mu:
            rule.seed_rng(self.seed, len(self._rules))
            self._rules.append(rule)
        return rule

    def remove_rule(self, rule: FaultRule) -> None:
        with self._mu:
            try:
                self._rules.remove(rule)
            except ValueError:
                pass

    def rules(self) -> List[FaultRule]:
        with self._mu:
            return list(self._rules)

    def fire(self, name: str, ctx: Dict[str, Any]) -> None:
        with self._mu:
            todo = [
                r for r in self._rules
                if r.matches(name, ctx) and r.decide()
            ]
        for r in todo:
            if r.action == "delay":
                time.sleep(r.delay_ms / 1000.0)
            elif r.action == "error":
                raise FaultInjected(name, r.action, sent=True)
            else:  # drop / partition
                raise FaultInjected(name, r.action, sent=False)


# ---------------------------------------------------------------------------
# process-wide installation
# ---------------------------------------------------------------------------

_install_mu = threading.Lock()
_plan: Optional[FaultPlan] = None
_env_checked = False


def install_plan(plan: Optional[FaultPlan]) -> Optional[FaultPlan]:
    """Install (or, with None, clear) the process-wide plan."""
    global _plan, _env_checked
    with _install_mu:
        _plan = plan
        _env_checked = True  # an explicit install overrides the env
    return plan


def install_spec(spec) -> FaultPlan:
    return install_plan(FaultPlan.from_spec(spec))


def clear() -> None:
    install_plan(None)


def get_plan() -> Optional[FaultPlan]:
    global _env_checked, _plan
    if not _env_checked:
        with _install_mu:
            if not _env_checked:
                import os

                raw = os.environ.get("XLLM_CHAOS_SPEC", "")
                if raw:
                    try:
                        _plan = FaultPlan.from_spec(raw)
                    except Exception:
                        _plan = None
                _env_checked = True
    return _plan


# Observer hook: obs/locktrace.py registers its sanitizer here when
# XLLM_LOCK_TRACE is on, so a lock held across an injection point — the
# place chaos can inject a hang WHILE the lock is held — is recorded
# without faults.py importing the tracer.
_point_observer: Optional[Any] = None


def set_point_observer(cb) -> None:
    global _point_observer
    _point_observer = cb


def point(name: str, /, **ctx: Any) -> None:
    """Mark one named injection point. No-op (one global read + None
    check each for the observer and the plan) unless a sanitizer or a
    plan is installed; may sleep or raise FaultInjected when a rule
    fires."""
    obs = _point_observer
    if obs is not None:
        obs(name)
    plan = get_plan()
    if plan is None:
        return
    plan.fire(name, ctx)
