"""Chained murmur3 KV-block hashing — THE cross-tier invariant.

The engine's cache events, the service's global cache index, and cache-aware
routing must all derive identical 16-byte keys for the same token prefix
(reference: xllm_service/common/hash_util.{h,cpp}; chaining walk in
global_kvcache_mgr.cpp:85-95). Contract:

    hash_0 = murmur3_x64_128(int32_le(tokens[0:B]), seed)
    hash_i = murmur3_x64_128(hash_{i-1} || int32_le(tokens[i*B:(i+1)*B]), seed)

with B = block_size (default 128) and seed default 1024
(reference: common/global_gflags.cpp:50-55, 94-96). Only *complete* blocks
are hashed.

Backed by the C++ cdylib in native/ (built on demand); a pure-Python
implementation serves as fallback and as an independent cross-check in tests.
"""

from __future__ import annotations

import ctypes
import os
import struct
import subprocess
import threading
from typing import List, Optional, Sequence

MURMUR3_VALUE_LEN = 16
DEFAULT_SEED = 1024  # reference: global_gflags.cpp:55
DEFAULT_BLOCK_SIZE = 128  # reference: global_gflags.cpp:94-96

_MASK64 = (1 << 64) - 1
_C1 = 0x87C37B91114253D5
_C2 = 0x4CF5AD432745937F


def _rotl64(x: int, r: int) -> int:
    return ((x << r) | (x >> (64 - r))) & _MASK64


def _fmix64(k: int) -> int:
    k ^= k >> 33
    k = (k * 0xFF51AFD7ED558CCD) & _MASK64
    k ^= k >> 33
    k = (k * 0xC4CEB9FE1A85EC53) & _MASK64
    k ^= k >> 33
    return k


def murmur3_x64_128_py(data: bytes, seed: int = DEFAULT_SEED) -> bytes:
    """Pure-Python MurmurHash3 x64_128 (little-endian output h1||h2)."""
    length = len(data)
    nblocks = length // 16
    h1 = seed & _MASK64
    h2 = seed & _MASK64

    for i in range(nblocks):
        k1, k2 = struct.unpack_from("<QQ", data, i * 16)
        k1 = (k1 * _C1) & _MASK64
        k1 = _rotl64(k1, 31)
        k1 = (k1 * _C2) & _MASK64
        h1 ^= k1
        h1 = _rotl64(h1, 27)
        h1 = (h1 + h2) & _MASK64
        h1 = (h1 * 5 + 0x52DCE729) & _MASK64
        k2 = (k2 * _C2) & _MASK64
        k2 = _rotl64(k2, 33)
        k2 = (k2 * _C1) & _MASK64
        h2 ^= k2
        h2 = _rotl64(h2, 31)
        h2 = (h2 + h1) & _MASK64
        h2 = (h2 * 5 + 0x38495AB5) & _MASK64

    tail = data[nblocks * 16 :]
    k1 = 0
    k2 = 0
    tl = len(tail)
    if tl > 8:
        for i in range(tl - 1, 7, -1):
            k2 = (k2 << 8) | tail[i]
        k2 = (k2 * _C2) & _MASK64
        k2 = _rotl64(k2, 33)
        k2 = (k2 * _C1) & _MASK64
        h2 ^= k2
    if tl > 0:
        for i in range(min(tl, 8) - 1, -1, -1):
            k1 = (k1 << 8) | tail[i]
        k1 = (k1 * _C1) & _MASK64
        k1 = _rotl64(k1, 31)
        k1 = (k1 * _C2) & _MASK64
        h1 ^= k1

    h1 ^= length
    h2 ^= length
    h1 = (h1 + h2) & _MASK64
    h2 = (h2 + h1) & _MASK64
    h1 = _fmix64(h1)
    h2 = _fmix64(h2)
    h1 = (h1 + h2) & _MASK64
    h2 = (h2 + h1) & _MASK64
    return struct.pack("<QQ", h1, h2)


# ---------------------------------------------------------------------------
# Native library loading (lazy, build-on-demand, thread-safe)
# ---------------------------------------------------------------------------

_NATIVE_DIR = os.path.join(os.path.dirname(__file__), "..", "native")
_LIB_PATH = os.path.abspath(os.path.join(_NATIVE_DIR, "libxllm_native.so"))
_SRC_PATH = os.path.abspath(os.path.join(_NATIVE_DIR, "murmur3.cpp"))

_lib: Optional[ctypes.CDLL] = None
_lib_lock = threading.Lock()
_lib_failed = False


def _load_native() -> Optional[ctypes.CDLL]:
    global _lib, _lib_failed
    if _lib is not None or _lib_failed:
        return _lib
    with _lib_lock:
        if _lib is not None or _lib_failed:
            return _lib
        try:
            if not os.path.exists(_LIB_PATH) or os.path.getmtime(
                _SRC_PATH
            ) > os.path.getmtime(_LIB_PATH):
                # Build to a per-pid temp then atomically rename so concurrent
                # processes never dlopen a half-written library.
                tmp = f"{_LIB_PATH}.{os.getpid()}.tmp"
                subprocess.run(
                    ["g++", "-O2", "-shared", "-fPIC", "-o", tmp, _SRC_PATH],
                    check=True,
                    capture_output=True,
                )
                os.replace(tmp, _LIB_PATH)
            lib = ctypes.CDLL(_LIB_PATH)
            lib.xllm_murmur3_x64_128.argtypes = [
                ctypes.c_void_p,
                ctypes.c_int,
                ctypes.c_uint32,
                ctypes.c_void_p,
            ]
            lib.xllm_block_hash.argtypes = [
                ctypes.c_char_p,
                ctypes.POINTER(ctypes.c_int32),
                ctypes.c_int,
                ctypes.c_uint32,
                ctypes.c_char_p,
            ]
            lib.xllm_prefix_block_hashes.argtypes = [
                ctypes.POINTER(ctypes.c_int32),
                ctypes.c_int,
                ctypes.c_int,
                ctypes.c_uint32,
                ctypes.c_char_p,
            ]
            lib.xllm_prefix_block_hashes.restype = ctypes.c_int
            _lib = lib
        except Exception:
            _lib_failed = True
    return _lib


def murmur3_x64_128(data: bytes, seed: int = DEFAULT_SEED) -> bytes:
    lib = _load_native()
    if lib is None:
        return murmur3_x64_128_py(data, seed)
    out = ctypes.create_string_buffer(MURMUR3_VALUE_LEN)
    lib.xllm_murmur3_x64_128(data, len(data), seed, out)
    return out.raw


def block_hash(
    prev_hash: Optional[bytes],
    token_ids: Sequence[int],
    seed: int = DEFAULT_SEED,
) -> bytes:
    """One chained step (reference: hash_util.cpp:18-44)."""
    payload = struct.pack(f"<{len(token_ids)}i", *token_ids)
    if prev_hash is not None:
        if len(prev_hash) != MURMUR3_VALUE_LEN:
            raise ValueError("prev_hash must be 16 bytes")
        payload = prev_hash + payload
    return murmur3_x64_128(payload, seed)


def prefix_block_hashes(
    token_ids: Sequence[int],
    block_size: int = DEFAULT_BLOCK_SIZE,
    seed: int = DEFAULT_SEED,
) -> List[bytes]:
    """Chained hashes of every complete block of the prefix
    (reference walk: global_kvcache_mgr.cpp:85-95)."""
    n = len(token_ids)
    num_blocks = n // block_size
    if num_blocks == 0:
        return []
    lib = _load_native()
    if lib is not None:
        arr = (ctypes.c_int32 * n)(*token_ids)
        out = ctypes.create_string_buffer(num_blocks * MURMUR3_VALUE_LEN)
        lib.xllm_prefix_block_hashes(arr, n, block_size, seed, out)
        raw = out.raw
        return [
            raw[i * MURMUR3_VALUE_LEN : (i + 1) * MURMUR3_VALUE_LEN]
            for i in range(num_blocks)
        ]
    hashes: List[bytes] = []
    prev: Optional[bytes] = None
    for b in range(num_blocks):
        h = block_hash(prev, token_ids[b * block_size : (b + 1) * block_size], seed)
        hashes.append(h)
        prev = h
    return hashes


def extend_prefix_block_hashes(
    hashes: List[bytes],
    token_ids: Sequence[int],
    nblocks: int,
    block_size: int = DEFAULT_BLOCK_SIZE,
    seed: int = DEFAULT_SEED,
) -> List[bytes]:
    """Extend a chained-hash list IN PLACE to `nblocks` blocks of
    `token_ids`, returning it. Chain-identical to prefix_block_hashes
    (same block_hash steps) — the incremental form for callers that grow
    a prefix block-by-block (the engine's chunked-prefill KV streaming)
    and must never pay the O(blocks) rehash per extension. Lives here so
    the chain semantics have exactly one home."""
    while len(hashes) < nblocks:
        b = len(hashes)
        hashes.append(
            block_hash(
                hashes[b - 1] if b else None,
                token_ids[b * block_size : (b + 1) * block_size],
                seed,
            )
        )
    return hashes
