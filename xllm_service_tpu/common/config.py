"""Framework configuration.

Replaces the reference's gflags + builder Options pair
(reference: common/global_gflags.cpp — ~23 flags; common/options.h:24-77)
with one frozen dataclass parsed from CLI/env. Defaults mirror the
reference's flag defaults (BASELINE.md anchors).
"""

from __future__ import annotations

import argparse
import dataclasses
import os
from dataclasses import dataclass, field
from typing import List, Optional


@dataclass
class ServiceConfig:
    """Service-tier (control plane) options."""

    # Server endpoints (reference: global_gflags.cpp ports).
    host: str = "0.0.0.0"
    http_port: int = 9888
    rpc_port: int = 9889

    # Concurrency (reference defaults 32 threads / 128 concurrency).
    num_threads: int = 32
    max_concurrency: int = 128
    num_ordered_output_streams: int = 128  # reference: scheduler.h:112

    # HTTP front-end backend: "event" = evserve selectors/epoll loop (SSE
    # streams hold sockets, not threads — the >1k-concurrent-streams path);
    # "threaded" = stdlib ThreadingHTTPServer (thread per connection).
    http_backend: str = "event"
    http_workers: int = 32  # event backend: route-handler pool size
    http_max_connections: int = 4096  # accept cap; extras are refused
    http_idle_timeout_s: float = 120.0  # keep-alive idle reap (<=0 disables)
    http_drain_timeout_s: float = 5.0  # stop(): grace for in-flight streams
    # Slow-client guard: per-connection SSE outbox cap. A client that falls
    # a full buffer behind its generation is dropped and the request
    # cancelled upstream, instead of buffering without bound.
    sse_max_buffered_kb: int = 512
    # Event backend request-body cap (413 past it). Must clear the largest
    # legitimate body — base64 multimodal parts run to ~100 MB of video.
    http_max_body_mb: int = 256

    # Coordination backend. "memory://" selects the in-process store;
    # "etcd://host:port" an external etcd (reference: --etcd_addr).
    etcd_addr: str = "memory://"

    # Routing policy: RR | CAR | SLO_AWARE (reference: --load_balance_policy).
    load_balance_policy: str = "RR"

    # KV block contract (reference: --block_size default 128,
    # --murmur_hash3_seed default 1024).
    block_size: int = 128
    murmur_hash3_seed: int = 1024

    # SLO targets, ms (reference: global_gflags.cpp:102-112).
    target_ttft_ms: float = 1000.0
    target_tpot_ms: float = 50.0

    # Liveness (reference: 3 s heartbeat / lease TTL; the 15 s
    # detect_disconnected_instance_interval flag is dead code there — here it
    # is real and prunes instances whose heartbeat stopped).
    heartbeat_interval_s: float = 3.0
    master_lease_ttl_s: float = 3.0
    detect_disconnected_instance_interval_s: float = 15.0
    # Floor on the instance-registration lease TTL (the TTL is otherwise
    # 3x the heartbeat interval). An engine whose heartbeat thread stalls
    # behind a long GIL-holding XLA trace/compile must not be pruned as
    # dead mid-generation; fault-injection tests that WANT fast expiry
    # lower this explicitly.
    instance_lease_min_ttl_s: float = 10.0

    # Fault hardening (docs/FAULT_TOLERANCE.md). Control-plane POSTs
    # (dispatch/cancel/encoder push) retry with jittered exponential
    # backoff up to this many attempts...
    dispatch_retry_attempts: int = 3
    # ...gated by a GLOBAL retry budget: every first attempt deposits
    # `ratio` tokens, every retry spends one (min_tokens floors the
    # bucket), so one flapping instance can't trigger a retry storm.
    retry_budget_ratio: float = 0.2
    retry_budget_min: float = 10.0
    # Circuit breaker: consecutive dispatch/cancel failures per instance
    # before it turns suspect (deprioritized) / ejected (unroutable until
    # an active /health probe passes).
    breaker_suspect_failures: int = 2
    breaker_eject_failures: int = 4
    # Mid-stream failover: total transparent replay attempts per request
    # (pre-first-token redispatch and token-replay resume share the
    # bound).
    max_redispatch: int = 2
    # Fenced master failover: instance-side TTL for in-flight manifests a
    # takeover reconciliation did NOT reclaim — past it the instance
    # reaps them (engine requests cancelled, blocks freed) so a dead
    # master's requests can never leak KV (docs/FAULT_TOLERANCE.md).
    reconcile_orphan_ttl_s: float = 10.0

    # Fleet-wide prefix KV fabric (docs/KV_CACHE.md): fetch-aware dispatch
    # hints, fetch-cost-adjusted CAR scoring, and coordinated multi-tier
    # eviction. The env var XLLM_PREFIX_FABRIC=1|0 overrides this field
    # either way (read per call, so the hatch flips on a live cluster).
    enable_prefix_fabric: bool = True

    # Goodput controller plane (cluster/goodput.py): per-request
    # colocate-vs-disaggregate placement plus continuous PD role
    # reshaping. The env var XLLM_GOODPUT_CONTROLLER=1|0 overrides this
    # field either way (read per call); when off or when its input
    # signals are stale the scheduler keeps today's static behavior.
    enable_goodput_controller: bool = True
    # Per-tenant admission control at the front door (service/admission.py):
    # token-bucket rate (req/s per tenant, 0 = unlimited), per-tenant and
    # global inflight caps, fair-share weighted queuing bounded by the
    # queue timeout (0 = shed immediately at the global cap), and
    # "tenant:weight,..." fair shares. XLLM_ADMISSION=1|0 overrides the
    # enable either way; each knob has a matching XLLM_ADMISSION_* hatch
    # read per call (docs/ARCHITECTURE.md).
    enable_admission_control: bool = True
    admission_rate: float = 0.0
    admission_burst: float = 0.0
    admission_max_inflight: int = 2048
    admission_max_global_inflight: int = 8192
    admission_queue_timeout_s: float = 2.0
    admission_weights: str = ""

    # Tokenizer / template (reference: --tokenizer_path).
    tokenizer_path: str = ""

    # Tracing (reference: --enable_request_trace).
    enable_request_trace: bool = False
    trace_dir: str = "trace"
    # Rotated trace.jsonl generations kept on disk (trace.jsonl.1..N).
    trace_keep: int = 1
    # Flight recorder (obs/flight.py, docs/OBSERVABILITY.md): always-on
    # span ring capacity per process, and the anomaly thresholds that
    # dump it — TTFT SLO in ms (0 disables the SLO trigger) and the KV
    # handoff stall bound in ms. Env hatches XLLM_TRACE_RING,
    # XLLM_TRACE_SLO_TTFT_MS and XLLM_TRACE_STALL_MS override these
    # fields either way (read at trigger time, so they flip live).
    trace_ring_capacity: int = 2048
    trace_slo_ttft_ms: float = 0.0
    trace_stall_ms: float = 2000.0

    # Decode→service direct response path (reference:
    # ENABLE_DECODE_RESPONSE_TO_SERVICE env, rpc_service/service.h:61-71).
    enable_decode_response_to_service: bool = True

    # EPD multimodal: placeholder tokens inserted per media part — must
    # match the encoder's VisionConfig.out_tokens.
    mm_tokens_per_media: int = 4
    # Real-image front door (service/image_processor.py): which HF
    # processor semantics to apply to data:image/... payloads before the
    # encode stage. "" rejects real images (raw-f32 tensor backdoor
    # only); "siglip" = resize+0.5-normalize; "qwen2vl" = smart-resize
    # pixel math pinned to the tower's square, CLIP normalize.
    mm_image_processor: str = ""
    # Square the ENCODE tower compiled for (VisionConfig.image_size);
    # required when mm_image_processor is set.
    mm_image_size: int = 0
    # Frames per temporal slice of the ENCODE tower
    # (VisionConfig.temporal_patch_size) — sizes video placeholder
    # spans: a T-frame video takes T/tps * mm_tokens_per_media tokens.
    mm_temporal_patch_size: int = 2
    # Uniform-sampling cap for real compressed videos (data:video/...):
    # longer clips sample down to this many frames before encoding.
    mm_video_max_frames: int = 16
    # Audio front door (service/audio_processor.py): the ENCODE audio
    # tower's log-mel geometry (AudioConfig.num_mel_bins / mel_frames).
    # 0 frames disables real-audio ingestion (raw-f32 backdoor only).
    mm_audio_mel_bins: int = 128
    mm_audio_mel_frames: int = 0

    # Encoder fabric (docs/EPD.md): media-hash-keyed embedding index +
    # hit/queue-aware encoder routing on the master, streamed
    # encoder->prefill handoff, and cross-request encoder batching on the
    # instances. The env var XLLM_ENCODER_FABRIC=1|0 overrides this field
    # either way (read per call, so the hatch flips on a live cluster);
    # every fabric failure degrades to the synchronous EPD path.
    enable_encoder_fabric: bool = True

    @classmethod
    def from_args(cls, argv: Optional[List[str]] = None) -> "ServiceConfig":
        parser = argparse.ArgumentParser("xllm-service-tpu master")
        for f in dataclasses.fields(cls):
            flag = "--" + f.name.replace("_", "-")
            if f.type == "bool" or isinstance(f.default, bool):
                parser.add_argument(
                    flag, type=lambda s: s.lower() in ("1", "true", "yes"),
                    default=f.default,
                )
            else:
                parser.add_argument(flag, type=type(f.default), default=f.default)
        ns = parser.parse_args(argv)
        return cls(**vars(ns))


@dataclass
class EngineConfig:
    """Engine-tier (TPU runtime) options for one instance."""

    model: str = "llama3-tiny"  # key into models/configs.py registry
    checkpoint_path: str = ""  # empty = random-init (tests/bench)
    dtype: str = "bfloat16"

    # Paged KV cache.
    block_size: int = 128  # tokens per KV block — must match service tier
    murmur_hash3_seed: int = 1024  # block-hash seed — must match service tier
    num_blocks: int = 0  # 0 = size from hbm_utilization
    hbm_utilization: float = 0.9  # fraction of HBM for params + KV pool
    # "auto" stores KV in model dtype; "int8" quantizes per (token, kv-head)
    # row — halves decode's HBM traffic and doubles pool capacity. The
    # block-hash contract is unaffected (hashes cover token ids, not bytes);
    # migration/host-tier payloads stay in model dtype (requantized on
    # import).
    kv_cache_dtype: str = "auto"
    # "auto" keeps matmul weights in model dtype; "int8" quantizes them
    # per output channel (ops/quant.py) — halves decode's weight HBM
    # traffic and per-device param residency (the 70B-on-v5e lever the
    # dress rehearsal budgets flag); "int4" packs two weights per byte
    # with group-wise scales (group 128 along the contracting axis) —
    # quarter-size weights, the DeepSeek-V3-scale-on-a-pod lever. All
    # model families.
    weight_dtype: str = "auto"

    # Continuous batching.
    max_running_requests: int = 64
    max_prefill_tokens: int = 8192  # per-step prefill token budget
    max_seq_len: int = 8192
    prefill_buckets: List[int] = field(
        default_factory=lambda: [128, 256, 512, 1024, 2048, 4096, 8192]
    )

    # Parallelism over the instance's mesh.
    dp_size: int = 1
    tp_size: int = 1
    ep_size: int = 1  # MoE expert parallelism (experts over an ep axis)
    sp_size: int = 1  # sequence/context parallelism (ring-attention prefill)
    # Prompts with at least this many uncached tokens prefill via the
    # sequence-parallel ring path (0 = never). Requires sp_size > 1.
    sp_prefill_threshold: int = 0

    # Sampling defaults.
    max_new_tokens_default: int = 512

    # Engine stepping mode. False (default) = overlapped one-step-lookahead
    # pipeline: decode step N+1 is dispatched while step N's sampled tokens
    # are still in flight on the device (they feed step N+1's inputs
    # device-side; the host drains results one step behind and discards the
    # single late token a stopped sequence over-produces). True = fully
    # synchronous stepping (every step fetched + booked before the next
    # dispatch) — the differential-testing / debugging escape hatch. The
    # env var XLLM_SYNC_ENGINE=1|0 overrides this field either way, and
    # the engine re-reads it EVERY step, so a flip takes effect on a
    # running engine at the next iteration (the in-flight step is
    # flushed at the transition — docs/ENGINE_PIPELINE.md).
    sync_engine: bool = False

    # Mixed (ragged) stepping. True (default) = the engine step builder
    # emits ONE batch per iteration — all active decode slots PLUS the due
    # chunked-prefill rows — served by a single compiled mixed step
    # (models.<family>.mixed_step via executor.mixed_start), so prefill
    # and decode stop competing for alternating engine steps
    # (docs/KERNELS.md). Whether the attention inside that step runs as
    # ONE ragged Pallas dispatch or as the split decode+prefill kernels is
    # a separate hatch (XLLM_RAGGED_ATTENTION_KERNEL — opt-in until
    # chip-validated). False = the split-step escape hatch (prefill batch
    # then decode step, the pre-ISSUE-9 hot loop). Env override
    # XLLM_MIXED_STEP=1|0 wins either way; sync iterations and MLA
    # families always run split. Guided requests ride the mixed batch
    # (their final chunk samples under an in-graph mask row), and
    # speculative engines fuse verify rows with the due prefill chunks
    # (mixed_verify_step) when enable_spec_pipeline holds.
    enable_mixed_step: bool = True

    # Speculative decoding (prompt-lookup / n-gram drafting; 0 disables).
    # Each decode step drafts this many tokens per sequence by matching the
    # newest suffix n-gram against the sequence's own history, verifies all
    # of them in ONE forward pass (static [R, k+1] shapes — no recompiles),
    # and emits 1..k+1 tokens. EXACT: point-mass drafts + the sequential
    # per-step key schedule make the emitted stream bit-identical to
    # non-speculative decoding under the same seeds (ops/sampling.py
    # speculative_sample). Decode is HBM-bound, so verifying k+1 positions
    # reuses the same weight/KV traffic one token would — accepted drafts
    # are nearly free throughput.
    speculative_tokens: int = 0
    speculative_ngram_max: int = 3  # longest suffix n-gram to match
    # Legacy scan bound for prompt-lookup drafting. The proposer keeps a
    # per-sequence rolling suffix index (O(ngram_max) per step), so this
    # only caps the one-off index build of a long RESUMED history; the
    # index itself covers the full history.
    speculative_lookback: int = 4096
    # Speculative decoding inside the overlapped pipeline. True (default)
    # = draft+verify runs as a pipelined unit: verify step N+1 is
    # dispatched while step N is in flight, with step N+1's inputs (last
    # accepted token, position, step count) gathered ON DEVICE from step
    # N's verify output — the variable accepted count never round-trips
    # the host. Exactness: point-mass acceptance makes the emitted
    # stream draft-independent, so host-proposed drafts may lag one step
    # without changing a byte (docs/ENGINE_PIPELINE.md). False = verify
    # steps run on the sync path (the pre-ISSUE-13 behavior). Env
    # override XLLM_SPEC_PIPELINE=1|0 wins either way, re-read per step.
    enable_spec_pipeline: bool = True

    # Persistent XLA compilation cache dir ("" disables). First boot of a
    # shape-bucketed engine compiles tens of programs at 20-40 s each on
    # TPU; with the cache, every later boot (restart, PD role flip to an
    # already-seen traffic shape, elastic scale-out on shared storage)
    # loads them in milliseconds — SURVEY.md §7 hard part 4.
    compilation_cache_dir: str = ""

    # Host offload (DRAM tier) blocks; 0 disables.
    num_host_blocks: int = 0
    # SSD tier: blocks spilled from the host pool to local disk; 0 disables.
    num_ssd_blocks: int = 0
    ssd_cache_dir: str = ""  # empty = <tempdir>/xllm-ssd-cache-<pid>

    # PD KV handoff to a decode peer in the SAME process goes through a
    # direct call (no serialization — single-host ICI-path analog) when
    # enabled; disable to force the HTTP data plane.
    enable_local_kv_transfer: bool = True

    # Pipelined PD handoff (docs/PD_DISAGGREGATION.md): stream each
    # prefill chunk's completed KV blocks to the decode peer WHILE the
    # next chunk is still prefilling, so only the tail rides the
    # post-prefill commit and the handoff stall shrinks to the tail +
    # control round-trip. Single-chunk prompts always take the monolithic
    # path; any session failure falls back to it too. The env var
    # XLLM_PD_STREAMING=1|0 overrides this field either way (the escape
    # hatch is read per request, so it can flip on a live instance).
    enable_pd_streaming: bool = True

    # Fleet-wide prefix KV fabric, instance side (docs/KV_CACHE.md): serve
    # peer /kv/fetch requests, act on dispatch fetch hints, and offer
    # last-replica evictions to the master's coordinator. The env var
    # XLLM_PREFIX_FABRIC=1|0 overrides either way, per request.
    enable_prefix_fabric: bool = True

    # Encoder fabric, instance side (docs/EPD.md): ENCODE instances grow a
    # cross-request micro-batcher + media-hash-keyed embedding LRU, and
    # the encoder->prefill handoff streams per-item sessions instead of
    # one monolithic /mm/import. XLLM_ENCODER_FABRIC=1|0 overrides either
    # way, per request; any failure degrades to the synchronous path.
    enable_encoder_fabric: bool = True
    # Micro-batcher admission window: an arriving media item waits at most
    # this long for same-kind items from OTHER requests before the tower
    # dispatch fires (deadline-bounded coalescing).
    encoder_batch_window_ms: float = 5.0
    # Micro-batcher size bound (power of two — the towers pad batches to
    # pow2, so a pow2 cut wastes no padding).
    encoder_batch_max: int = 8
    # Encoder-local embedding LRU capacity, in media items (0 disables
    # caching; the master's fleet index follows via heartbeat deltas).
    encoder_cache_entries: int = 256
    # Prefill side: how long an admitted media request may wait for its
    # streamed embeddings before it is rejected (generous — the encoder's
    # first request pays its XLA compile inside this window).
    mm_stream_deadline_s: float = 180.0

    # Cross-PROCESS device-to-device KV data plane
    # (jax.experimental.transfer). When enabled, PD handoffs to a peer in
    # another process are OFFERED on this process's transfer server and
    # pulled by the peer straight into its device memory — the payload
    # never stages through host RAM on either side (the reference's
    # engine-to-engine RDMA pull, types.h:174-177). Disabled: payload
    # bytes ride the /kv/import POST body.
    enable_kv_transfer_server: bool = False
    kv_transfer_listen: str = "127.0.0.1:0"

    # Multi-host process group (jax.distributed). Non-empty
    # coordinator_address bootstraps the group before the mesh is built;
    # jax.devices() then spans ALL hosts and dp/tp/ep/sp shardings ride
    # ICI within a slice and DCN across hosts. num_processes/process_id
    # may stay 0/-1 on real TPU pods (auto-discovered from metadata).
    coordinator_address: str = ""
    num_processes: int = 0
    process_id: int = -1

    # Compile the serving step functions (per-bucket prefill + decode)
    # BEFORE the instance registers, so the first real request never pays
    # a compile in its TTFT.
    warmup_on_start: bool = False

    # Instance identity/role.
    instance_name: str = ""
    instance_type: str = "MIX"  # DEFAULT | PREFILL | DECODE | MIX | ENCODE

    # Instance HTTP front door backend ("threaded" | "event"); the service
    # tier's equivalent knob is ServiceConfig.http_backend. Threaded stays
    # the default here: direct-mode streaming handlers block their worker,
    # so the event loop's pool would cap direct-mode concurrency.
    http_backend: str = "threaded"
