"""Control-plane data structures with JSON serde.

TPU-native re-design of the reference's core types
(reference: xllm_service/common/types.h:39-411). JSON field names are kept
wire-compatible with the reference's `serialize_to_json()` output so that a
coordination store written by either implementation parses in the other.
Divergences (deliberate, per SURVEY.md §7 "quirks"):
  * float scoring everywhere (the reference's integer-division cost terms
    truncate to 0 — cache_aware_routing.cpp:73-78);
  * `CacheLocations` tier attribution is correct for DRAM/SSD (the reference
    reads `hbm_instance_set.begin()` in those branches —
    global_kvcache_mgr.cpp:108-125);
  * an ENCODE instance type exists for the EPD multimodal three-stage path.
"""

from __future__ import annotations

import dataclasses
import enum
import json
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Set, Tuple


class ErrorCode(enum.IntEnum):
    # reference: common/types.h:53-58
    OK = 0
    INTERNAL_ERROR = 1
    INSTANCE_EXISTED = 2
    INSTANCE_NOT_EXISTED = 3


class InstanceType(enum.IntEnum):
    """Engine-instance role (reference: common/types.h:71-79).

    ENCODE (=4) is new: the multimodal encoder stage of EPD three-stage
    disaggregation (the reference carries only vestiges of this —
    chat_template MMContent, jinja_chat_template.h:30-47).
    """

    DEFAULT = 0
    PREFILL = 1
    DECODE = 2
    MIX = 3
    ENCODE = 4

    @classmethod
    def parse(cls, v: "InstanceType | int | str") -> "InstanceType":
        if isinstance(v, InstanceType):
            return v
        if isinstance(v, int):
            return cls(v)
        return cls[v.upper()]


@dataclass
class Routing:
    """PD(+E) instance assignment for one request (reference: types.h:39-51)."""

    prefill_name: str = ""
    decode_name: str = ""
    # EPD extension: encoder-stage instance (empty = no encode stage).
    encode_name: str = ""

    def to_json(self) -> Dict[str, Any]:
        j: Dict[str, Any] = {
            "prefill_name": self.prefill_name,
            "decode_name": self.decode_name,
        }
        if self.encode_name:
            j["encode_name"] = self.encode_name
        return j

    @classmethod
    def from_json(cls, j: Dict[str, Any]) -> "Routing":
        return cls(
            prefill_name=j.get("prefill_name", ""),
            decode_name=j.get("decode_name", ""),
            encode_name=j.get("encode_name", ""),
        )

    def debug_string(self) -> str:
        return json.dumps(self.to_json(), indent=2)


@dataclass
class LoadMetrics:
    """Instance load snapshot (reference: types.h:81-115).

    `gpu_cache_usage_perc` keeps the reference wire name; on TPU it reports
    HBM KV-cache pool usage in [0, 1].
    """

    waiting_requests_num: int = 0
    gpu_cache_usage_perc: float = 0.0
    # Hottest expert's share of routed MoE assignments (0.0 for dense
    # models / grouped dispatch off) — the expert-hotness signal the
    # master's routing can weigh next to cache hits (ISSUE 15,
    # docs/MOE.md). Optional on the wire: old-build instances simply
    # report 0.0.
    moe_hot_expert_frac: float = 0.0
    # EWMA of observed KV handoff stall per pulled request, milliseconds
    # (the xllm_kv_handoff_stall_ms stream folded into one scalar) — the
    # goodput controller's live disaggregation-cost signal. Optional on
    # the wire: old-build instances report 0.0 (= "no stall observed").
    kv_stall_ms_ewma: float = 0.0

    def to_json(self) -> Dict[str, Any]:
        return {
            "waiting_requests_num": self.waiting_requests_num,
            "gpu_cache_usage_perc": self.gpu_cache_usage_perc,
            "moe_hot_expert_frac": self.moe_hot_expert_frac,
            "kv_stall_ms_ewma": self.kv_stall_ms_ewma,
        }

    @classmethod
    def from_json(cls, j: Dict[str, Any]) -> "LoadMetrics":
        return cls(
            waiting_requests_num=int(j["waiting_requests_num"]),
            gpu_cache_usage_perc=float(j["gpu_cache_usage_perc"]),
            moe_hot_expert_frac=float(j.get("moe_hot_expert_frac", 0.0)),
            kv_stall_ms_ewma=float(j.get("kv_stall_ms_ewma", 0.0)),
        )


@dataclass
class LatencyMetrics:
    """Recent-window latency maxima, milliseconds (reference: types.h:117-127)."""

    recent_max_ttft: int = 0
    recent_max_tbt: int = 0

    def to_json(self) -> Dict[str, Any]:
        return {
            "recent_max_ttft": self.recent_max_ttft,
            "recent_max_tbt": self.recent_max_tbt,
        }

    @classmethod
    def from_json(cls, j: Dict[str, Any]) -> "LatencyMetrics":
        return cls(
            recent_max_ttft=int(j["recent_max_ttft"]),
            recent_max_tbt=int(j["recent_max_tbt"]),
        )


class RequestAction(enum.IntEnum):
    # reference: types.h:129-135
    SCHEDULE = 0
    FINISH_PREFILL = 1
    GENERATE = 2
    FINISH_DECODE = 3
    CANCEL = 4


@dataclass
class RequestMetrics:
    """Per-instance request bookkeeping driven by the 5-action state machine
    (reference: types.h:137-155; transitions in instance_mgr.cpp:582-654)."""

    prefill_request_num: int = 0
    prefill_token_num: int = 0
    decode_request_num: int = 0
    decode_token_num: int = 0
    # Estimated execution time for all queued prefill work, milliseconds.
    estimated_prefill_time: float = 0.0


@dataclass
class InstanceMetaInfo:
    """Instance registration record (reference: types.h:157-270).

    TPU mapping of the KV-transfer handles: `cluster_ids` become global slice
    ids, `addrs` the per-host transfer-server addresses, and
    `k_cache_ids`/`v_cache_ids` opaque per-layer buffer handles the peer uses
    to pull KV blocks over ICI/DCN (the reference relays the RDMA analogs of
    these without interpreting them — types.h:174-177).
    """

    name: str = ""
    rpc_address: str = ""
    http_address: str = ""
    # Served model id, surfaced through /v1/models (engine-side metadata the
    # reference never carries because its engines are absent).
    model_name: str = ""
    type: InstanceType = InstanceType.DEFAULT
    cluster_ids: List[int] = field(default_factory=list)
    addrs: List[str] = field(default_factory=list)
    k_cache_ids: List[int] = field(default_factory=list)
    v_cache_ids: List[int] = field(default_factory=list)
    dp_size: int = 1
    tp_size: int = 1
    # [(prompt_len, ttft_ms)]
    ttft_profiling_data: List[Tuple[int, float]] = field(default_factory=list)
    # [(batch_size, total_tokens, tpot_ms)]
    tpot_profiling_data: List[Tuple[int, int, float]] = field(default_factory=list)
    latest_timestamp: int = field(default_factory=lambda: int(time.time() * 1000))
    instance_index: int = -1
    # Current role of a MIX instance (SLO-aware PD flipping; types.h:192-194).
    current_type: InstanceType = InstanceType.PREFILL
    # LoRA adapter names this instance serves (requests with model=<name>
    # route to the adapter; surfaced cluster-wide via /v1/models).
    lora_adapters: List[str] = field(default_factory=list)
    # ENCODE instances: media modalities this encoder serves ("image",
    # "video", "audio") — the scheduler routes media requests only to an
    # encoder covering every requested modality. Empty = legacy wildcard.
    modalities: List[str] = field(default_factory=list)

    def to_json(self) -> Dict[str, Any]:
        return {
            "name": self.name,
            "rpc_address": self.rpc_address,
            "http_address": self.http_address,
            "model": self.model_name,
            "type": int(self.type),
            "addrs": self.addrs,
            "cluster_ids": self.cluster_ids,
            "k_cache_ids": self.k_cache_ids,
            "v_cache_ids": self.v_cache_ids,
            "dp_size": self.dp_size,
            "tp_size": self.tp_size,
            "ttft_profiling_data": [list(p) for p in self.ttft_profiling_data],
            "tpot_profiling_data": [list(p) for p in self.tpot_profiling_data],
            "latest_timestamp": self.latest_timestamp,
            "current_type": int(self.current_type),
            "lora_adapters": list(self.lora_adapters),
            "modalities": list(self.modalities),
        }

    @classmethod
    def from_json(cls, j: Dict[str, Any]) -> "InstanceMetaInfo":
        return cls(
            name=j.get("name", ""),
            rpc_address=j.get("rpc_address", ""),
            http_address=j.get("http_address", ""),
            model_name=j.get("model", ""),
            type=InstanceType(int(j.get("type", 0))),
            cluster_ids=[int(x) for x in j.get("cluster_ids", [])],
            addrs=list(j.get("addrs", [])),
            k_cache_ids=[int(x) for x in j.get("k_cache_ids", [])],
            v_cache_ids=[int(x) for x in j.get("v_cache_ids", [])],
            dp_size=int(j.get("dp_size", 1)),
            tp_size=int(j.get("tp_size", 1)),
            ttft_profiling_data=[
                (int(p[0]), float(p[1])) for p in j.get("ttft_profiling_data", [])
            ],
            tpot_profiling_data=[
                (int(p[0]), int(p[1]), float(p[2]))
                for p in j.get("tpot_profiling_data", [])
            ],
            latest_timestamp=int(j.get("latest_timestamp", 0)),
            current_type=InstanceType(int(j.get("current_type", 1))),
            lora_adapters=[str(x) for x in j.get("lora_adapters", [])],
            modalities=[str(x) for x in j.get("modalities", [])],
        )

    def serialize(self) -> str:
        return json.dumps(self.to_json())

    @classmethod
    def deserialize(cls, s: str) -> "InstanceMetaInfo":
        return cls.from_json(json.loads(s))


@dataclass
class CacheLocations:
    """Which instances hold a KV block, per memory tier
    (reference: types.h:272-317). On TPU the tiers are HBM (device),
    DRAM (host offload), SSD (local NVMe)."""

    hbm_instance_set: Set[str] = field(default_factory=set)
    dram_instance_set: Set[str] = field(default_factory=set)
    ssd_instance_set: Set[str] = field(default_factory=set)

    def to_json(self) -> Dict[str, Any]:
        return {
            "hbm_instance_set": sorted(self.hbm_instance_set),
            "dram_instance_set": sorted(self.dram_instance_set),
            "ssd_instance_set": sorted(self.ssd_instance_set),
        }

    @classmethod
    def from_json(cls, j: Dict[str, Any]) -> "CacheLocations":
        return cls(
            hbm_instance_set=set(j.get("hbm_instance_set", [])),
            dram_instance_set=set(j.get("dram_instance_set", [])),
            ssd_instance_set=set(j.get("ssd_instance_set", [])),
        )

    def empty(self) -> bool:
        return not (
            self.hbm_instance_set or self.dram_instance_set or self.ssd_instance_set
        )


@dataclass
class KvCacheEvent:
    """Heartbeat-carried KV-cache delta from an engine instance
    (reference: proto/xllm_rpc_service.proto:44-48). Hash values are the
    16-byte chained murmur3 block keys (common/hashing.py)."""

    stored_cache: Set[bytes] = field(default_factory=set)
    removed_cache: Set[bytes] = field(default_factory=set)
    # Blocks moved to a colder tier: hash -> tier name ("dram" | "ssd").
    offload_cache: Dict[bytes, str] = field(default_factory=dict)

    def empty(self) -> bool:
        return not (self.stored_cache or self.removed_cache or self.offload_cache)

    def merge(self, newer: "KvCacheEvent") -> "KvCacheEvent":
        """Fold a NEWER delta onto this one (self happened first). Used to
        re-merge an undelivered heartbeat delta with the next beat's so a
        failed POST never loses stored/removed transitions."""
        stored = (self.stored_cache - newer.removed_cache) | newer.stored_cache
        # A hash the newer delta stores OR offloads is alive again — an old
        # removal must not survive the merge (the master applies removed
        # last and would delete the live location).
        removed = (
            self.removed_cache
            - newer.stored_cache
            - set(newer.offload_cache)
        ) | newer.removed_cache
        offload = {**self.offload_cache, **newer.offload_cache}
        for h in newer.stored_cache | newer.removed_cache:
            offload.pop(h, None)
        return KvCacheEvent(
            stored_cache=stored, removed_cache=removed, offload_cache=offload
        )

    def to_json(self) -> Dict[str, Any]:
        return {
            "stored_cache": [h.hex() for h in sorted(self.stored_cache)],
            "removed_cache": [h.hex() for h in sorted(self.removed_cache)],
            "offload_cache": {h.hex(): t for h, t in self.offload_cache.items()},
        }

    @classmethod
    def from_json(cls, j: Dict[str, Any]) -> "KvCacheEvent":
        return cls(
            stored_cache={bytes.fromhex(h) for h in j.get("stored_cache", [])},
            removed_cache={bytes.fromhex(h) for h in j.get("removed_cache", [])},
            offload_cache={
                bytes.fromhex(h): t for h, t in j.get("offload_cache", {}).items()
            },
        )


@dataclass
class OverlapScores:
    """Prefix-cache match result per candidate instance
    (reference: types.h:319-355): instance name -> matched block count,
    per tier."""

    hbm_scores: Dict[str, int] = field(default_factory=dict)
    dram_scores: Dict[str, int] = field(default_factory=dict)
    ssd_scores: Dict[str, int] = field(default_factory=dict)
    total_blocks: int = 0

    def best(self) -> Tuple[str, int]:
        """Highest-scoring instance across tiers, HBM-weighted first."""
        best_name, best_score = "", -1
        for scores, weight in (
            (self.hbm_scores, 1.0),
            (self.dram_scores, 0.5),
            (self.ssd_scores, 0.25),
        ):
            for name, cnt in scores.items():
                s = cnt * weight
                if s > best_score:
                    best_name, best_score = name, s
        return best_name, best_score


@dataclass
class LoadBalanceInfos:
    """Inputs the cache-aware policy scores per candidate
    (reference: types.h:357-389)."""

    overlap_scores: OverlapScores = field(default_factory=OverlapScores)
    load_metrics: Dict[str, LoadMetrics] = field(default_factory=dict)
    max_waiting_requests_num: int = 0


# ---------------------------------------------------------------------------
# Engine result types (reference: common/xllm/output.h, status.h)
# ---------------------------------------------------------------------------


class StatusCode(enum.IntEnum):
    # reference: common/xllm/status.h:26-45
    OK = 0
    CANCELLED = 1
    UNKNOWN = 2
    INVALID_ARGUMENT = 3
    DEADLINE_EXCEEDED = 4
    RESOURCE_EXHAUSTED = 8
    UNAVAILABLE = 14


@dataclass
class Status:
    code: StatusCode = StatusCode.OK
    message: str = ""

    def ok(self) -> bool:
        return self.code == StatusCode.OK


class FinishReason(enum.Enum):
    # reference: common/xllm/output.h:31-37
    NONE = None
    STOP = "stop"
    LENGTH = "length"
    FUNCTION_CALL = "function_call"

    def to_string(self) -> Optional[str]:
        return self.value


@dataclass
class Usage:
    # reference: common/xllm/output.h:39-48
    num_prompt_tokens: int = 0
    num_generated_tokens: int = 0

    @property
    def num_total_tokens(self) -> int:
        return self.num_prompt_tokens + self.num_generated_tokens


@dataclass
class LogProbData:
    # reference: common/xllm/output.h:50-56
    token: str = ""
    token_id: int = 0
    logprob: float = 0.0


@dataclass
class LogProb:
    # reference: common/xllm/output.h:58-63
    data: LogProbData = field(default_factory=LogProbData)
    top_logprobs: List[LogProbData] = field(default_factory=list)


@dataclass
class SequenceOutput:
    # reference: common/xllm/output.h:66-81
    index: int = 0
    text: str = ""
    token_ids: List[int] = field(default_factory=list)
    finish_reason: FinishReason = FinishReason.NONE
    logprobs: List[LogProb] = field(default_factory=list)


@dataclass
class RequestOutput:
    # reference: common/xllm/output.h:83-108
    request_id: str = ""
    service_request_id: str = ""
    status: Status = field(default_factory=Status)
    outputs: List[SequenceOutput] = field(default_factory=list)
    usage: Optional[Usage] = None
    finished: bool = False
    cancelled: bool = False


# Callback invoked per generation step; returns False to cancel the stream
# (reference: common/xllm/output.h:131).
OutputCallback = Callable[[RequestOutput], bool]


@dataclass
class TraceContext:
    """Distributed-tracing context carried on every master->instance RPC
    and peer-to-peer plane (docs/OBSERVABILITY.md, Distributed tracing):
    dispatch forward, PD handoff/stream, fabric fetch, encoder forward,
    and the mm stream all ride a `trace` wire field so every participant
    stamps its spans under ONE trace id. `trace_id` is the base
    service_request_id (stable across redispatch attempts); `parent_span`
    names the stage of the emitting hop; `origin_epoch` is the
    dispatching master's fencing epoch, so a collector can tell spans of
    a deposed master's attempt apart from the successor's."""

    trace_id: str = ""
    parent_span: str = ""
    origin_epoch: int = 0

    def to_json(self) -> Dict:
        j: Dict = {"trace_id": self.trace_id}
        if self.parent_span:
            j["parent_span"] = self.parent_span
        if self.origin_epoch:
            j["origin_epoch"] = int(self.origin_epoch)
        return j

    @staticmethod
    def from_json(j) -> Optional["TraceContext"]:
        if not isinstance(j, dict) or not j.get("trace_id"):
            return None
        try:
            epoch = int(j.get("origin_epoch", 0) or 0)
        except (TypeError, ValueError):
            epoch = 0
        return TraceContext(
            trace_id=str(j["trace_id"]),
            parent_span=str(j.get("parent_span", "")),
            origin_epoch=epoch,
        )

    def child(self, parent_span: str) -> "TraceContext":
        """Same trace, re-parented for the next hop."""
        return TraceContext(self.trace_id, parent_span, self.origin_epoch)
