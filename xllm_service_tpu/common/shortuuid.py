"""Short request-id generation (reference: common/xllm/uuid.{h,cpp} — 22-char
base62 UUID via absl::BitGen). Same shape: 22 chars from [0-9A-Za-z]."""

from __future__ import annotations

import secrets
import string
import threading

_ALPHABET = string.digits + string.ascii_uppercase + string.ascii_lowercase
_LEN = 22


def generate_uuid(length: int = _LEN) -> str:
    return "".join(secrets.choice(_ALPHABET) for _ in range(length))


def generate_service_request_id(method: str) -> str:
    """'{method}-{thread_id}-{uuid22}' (reference: http_service/service.cpp:41-48)."""
    return f"{method}-{threading.get_ident() & 0xFFFF}-{generate_uuid()}"
