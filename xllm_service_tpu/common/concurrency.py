"""Thread-ownership contracts for single-threaded hot state.

The engine's slot arrays, block manager, and host/SSD pools are
engine-thread-only by design (docs/ENGINE_PIPELINE.md, docs/KV_CACHE.md
"the export itself runs on the engine thread") — but until now that
contract lived in docstrings. This module makes it executable:

    class Engine:
        def _loop(self):
            claim_thread(self, "engine")
            try:
                ...
            finally:
                release_thread(self, "engine")

        @thread_owned("engine")
        def _slot_admit(self, seq): ...

`@thread_owned(realm)` asserts, when `XLLM_THREAD_CHECKS=1` (the test
suite turns it on in tests/conftest.py), that the caller IS the thread
that claimed the realm on this object. Before any claim — unit tests
driving engine internals directly, sync-mode engines stepped inline —
the check passes: ownership only binds once a loop declares itself.
After `release_thread` (loop exit) direct calls are again allowed,
so a stopped engine can be inspected.

With checks off (production default) the decorator returns the function
untouched — zero overhead. The static half is graftlint's
thread-ownership pass (docs/STATIC_ANALYSIS.md): call sites of owned
methods must themselves be owned or claimers, so the whole engine-thread
call chain is marked and an off-thread call site fails lint before a
racy test has to catch it.
"""

from __future__ import annotations

import functools
import os
import threading

__all__ = [
    "checks_enabled",
    "claim_thread",
    "release_thread",
    "thread_owned",
    "ThreadOwnershipError",
]


class ThreadOwnershipError(AssertionError):
    """A @thread_owned method ran on a thread that doesn't own its realm."""


def checks_enabled() -> bool:
    return os.environ.get("XLLM_THREAD_CHECKS", "") not in ("", "0")


def _attr(realm: str) -> str:
    return f"_thread_owner_{realm}"


def claim_thread(obj, realm: str) -> None:
    """Declare the current thread the owner of `realm` on `obj` (the
    engine loop calls this first thing). Idempotent per thread;
    re-claiming from a DIFFERENT thread is itself an ownership bug."""
    cur = threading.get_ident()
    prev = getattr(obj, _attr(realm), None)
    if prev is not None and prev != cur and checks_enabled():
        raise ThreadOwnershipError(
            f"{type(obj).__name__}: realm {realm!r} already claimed by "
            f"thread {prev}; thread {cur} cannot re-claim it"
        )
    setattr(obj, _attr(realm), cur)


def release_thread(obj, realm: str) -> None:
    """Release ownership (loop exit): direct calls are allowed again."""
    try:
        delattr(obj, _attr(realm))
    except AttributeError:
        pass


def thread_owned(realm: str):
    """Methods mutating `realm`-owned state may only run on the claiming
    thread. No-op (function returned untouched) unless
    XLLM_THREAD_CHECKS=1 at decoration time."""

    def deco(fn):
        if not checks_enabled():
            return fn

        @functools.wraps(fn)
        def wrapper(self, *args, **kwargs):
            owner = getattr(self, _attr(realm), None)
            if owner is not None and owner != threading.get_ident():
                raise ThreadOwnershipError(
                    f"{type(self).__name__}.{fn.__name__} is "
                    f"@thread_owned({realm!r}) but ran on thread "
                    f"{threading.current_thread().name!r} while thread "
                    f"id {owner} owns the realm"
                )
            return fn(self, *args, **kwargs)

        return wrapper

    return deco
