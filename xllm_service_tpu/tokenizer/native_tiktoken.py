"""Native tiktoken tokenizer family.

The reference implements a tiktoken tokenizer natively
(xllm_service/tokenizer/tiktoken_tokenizer.{h,cpp}: base64 "token rank"
vocab file, re2 pre-tokenization regex, special-token regex, rank-ordered
byte-pair merging). This is the rebuild's native family for that path:
`native/tiktoken_core.cpp` owns the merge loop and vocab tables behind a
ctypes C ABI; this wrapper parses the base64 vocab file, runs the unicode
regex split (the `regex` module speaks \\p{L} classes), and splits
special tokens out of the text before merging — same division of labor
as tokenizer/native_bpe.py.

Model-dir detection: a `*.tiktoken` vocab file (Qwen-style dirs ship
`qwen.tiktoken`). Special tokens come from tokenizer_config.json's
added_tokens_decoder / special-token fields; the split pattern defaults
to the cl100k/Qwen pattern (the dirs don't carry it — same assumption
the reference's TokenizerArgs encode).
"""

from __future__ import annotations

import base64
import ctypes
import functools
import glob
import json
import os
from typing import Dict, List, Optional, Sequence

import regex as _regex

from xllm_service_tpu.tokenizer._native_build import (
    build_and_load,
    named_token_str,
)
from xllm_service_tpu.tokenizer.tokenizer import Tokenizer

_NATIVE_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "native"
)
_SRC = os.path.join(_NATIVE_DIR, "tiktoken_core.cpp")
_LIB = os.path.join(_NATIVE_DIR, "libxllm_tk.so")

# cl100k_base / Qwen split pattern.
_CL100K_PAT = (
    r"(?i:'s|'t|'re|'ve|'m|'ll|'d)|[^\r\n\p{L}\p{N}]?\p{L}+|\p{N}{1,3}"
    r"| ?[^\s\p{L}\p{N}]+[\r\n]*|\s*[\r\n]+|\s+(?!\S)|\s+"
)


@functools.lru_cache(maxsize=1)
def _load_lib() -> Optional[ctypes.CDLL]:
    lib = build_and_load(_SRC, _LIB)
    if lib is None:
        return None
    lib.tk_create.restype = ctypes.c_void_p
    lib.tk_destroy.argtypes = [ctypes.c_void_p]
    lib.tk_add.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int64, ctypes.c_int32
    ]
    lib.tk_encode_word.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int64,
        ctypes.POINTER(ctypes.c_int32), ctypes.c_int,
    ]
    lib.tk_encode_word.restype = ctypes.c_int
    lib.tk_decode.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_int32), ctypes.c_int,
        ctypes.c_char_p, ctypes.c_int,
    ]
    lib.tk_decode.restype = ctypes.c_int
    lib.tk_token_to_id.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int64
    ]
    lib.tk_token_to_id.restype = ctypes.c_int
    lib.tk_id_to_token.argtypes = [
        ctypes.c_void_p, ctypes.c_int32, ctypes.c_char_p, ctypes.c_int
    ]
    lib.tk_id_to_token.restype = ctypes.c_int
    return lib


class NativeTiktokenTokenizer(Tokenizer):
    def __init__(self, path: str, vocab_file: str):
        lib = _load_lib()
        assert lib is not None
        self._lib = lib
        self._h = lib.tk_create()
        max_id = -1
        with open(vocab_file, "rb") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                tok_b64, _, rank_s = line.partition(b" ")
                tok = base64.b64decode(tok_b64)
                rank = int(rank_s)
                lib.tk_add(self._h, tok, len(tok), rank)
                max_id = max(max_id, rank)

        self._pat = _regex.compile(_CL100K_PAT)
        self.bos_token: Optional[str] = None
        self.eos_token: Optional[str] = None
        self.chat_template: Optional[str] = None
        self._specials: Dict[str, int] = {}
        self._strip_ids: set = set()
        cfg_path = os.path.join(path, "tokenizer_config.json")
        if os.path.isfile(cfg_path):
            with open(cfg_path, encoding="utf-8") as f:
                cfg = json.load(f)
            # Special tokens append after the base vocab unless the config
            # carries explicit ids (added_tokens_decoder keys ARE the ids).
            for sid, spec in sorted(
                (cfg.get("added_tokens_decoder") or {}).items(),
                key=lambda kv: int(kv[0]),
            ):
                s = spec.get("content") if isinstance(spec, dict) else spec
                if isinstance(s, str):
                    self._specials[s] = int(sid)
                    max_id = max(max_id, int(sid))
                    # Only special=True tokens are STRIPPED on decode;
                    # non-special added tokens (tool markers etc.) are
                    # user-visible text (native_bpe gates the same way).
                    if not isinstance(spec, dict) or spec.get(
                        "special", True
                    ):
                        self._strip_ids.add(int(sid))
            self.bos_token = named_token_str(cfg.get("bos_token"))
            self.eos_token = named_token_str(cfg.get("eos_token"))
            ct = cfg.get("chat_template")
            if isinstance(ct, str):
                self.chat_template = ct
        self._vocab = max_id + 1
        self._special_ids = {v: k for k, v in self._specials.items()}
        self._special_re = (
            _regex.compile(
                "|".join(
                    _regex.escape(s)
                    for s in sorted(self._specials, key=len, reverse=True)
                )
            )
            if self._specials
            else None
        )

    def __del__(self):
        h = getattr(self, "_h", None)
        if h:
            self._lib.tk_destroy(h)
            self._h = None

    # ------------------------------------------------------------- encode
    def _encode_word(self, data: bytes) -> List[int]:
        cap = max(8, len(data))
        while True:
            buf = (ctypes.c_int32 * cap)()
            n = self._lib.tk_encode_word(self._h, data, len(data), buf, cap)
            if n == -(2**31):
                raise ValueError("tiktoken vocab is missing a byte entry")
            if n < 0:
                cap = -n
                continue
            return list(buf[:n])

    def _encode_plain(self, text: str) -> List[int]:
        out: List[int] = []
        for m in self._pat.finditer(text):
            out.extend(self._encode_word(m.group(0).encode("utf-8")))
        return out

    def encode(self, text: str) -> List[int]:
        if self._special_re is None:
            return self._encode_plain(text)
        out: List[int] = []
        pos = 0
        for m in self._special_re.finditer(text):
            if m.start() > pos:
                out.extend(self._encode_plain(text[pos:m.start()]))
            out.append(self._specials[m.group(0)])
            pos = m.end()
        if pos < len(text):
            out.extend(self._encode_plain(text[pos:]))
        return out

    def decode(self, ids: Sequence[int], skip_special_tokens: bool = True) -> str:
        # Specials live OUTSIDE the byte vocab: stitch segments.
        parts: List[bytes] = []
        seg: List[int] = []

        def flush():
            if not seg:
                return
            arr = (ctypes.c_int32 * len(seg))(*seg)
            cap = max(16, len(seg) * 8)
            while True:
                out = ctypes.create_string_buffer(cap)
                n = self._lib.tk_decode(self._h, arr, len(seg), out, cap)
                if n < 0:
                    cap = -n
                    continue
                parts.append(out.raw[:n])
                break
            seg.clear()

        for i in ids:
            s = self._special_ids.get(int(i))
            if s is not None:
                flush()
                if not skip_special_tokens or int(i) not in self._strip_ids:
                    parts.append(s.encode("utf-8"))
            else:
                seg.append(int(i))
        flush()
        return b"".join(parts).decode("utf-8", errors="replace")

    def id_to_token(self, token_id: int) -> str:
        s = self._special_ids.get(int(token_id))
        if s is not None:
            return s
        buf = ctypes.create_string_buffer(512)
        n = self._lib.tk_id_to_token(self._h, int(token_id), buf, 512)
        return buf.raw[:n].decode("utf-8", errors="replace") if n >= 0 else ""

    def token_to_id(self, token: str) -> Optional[int]:
        if token in self._specials:
            return self._specials[token]
        data = token.encode("utf-8")
        i = self._lib.tk_token_to_id(self._h, data, len(data))
        return None if i < 0 else i

    @property
    def vocab_size(self) -> int:
        return self._vocab

    @property
    def bos_token_id(self) -> Optional[int]:
        return self.token_to_id(self.bos_token) if self.bos_token else None

    @property
    def eos_token_id(self) -> Optional[int]:
        return self.token_to_id(self.eos_token) if self.eos_token else None


def try_load(path: str) -> Optional[NativeTiktokenTokenizer]:
    """A NativeTiktokenTokenizer for this model dir, or None when there is
    no .tiktoken vocab file or the native lib can't build."""
    if _load_lib() is None:
        return None
    files = sorted(glob.glob(os.path.join(path, "*.tiktoken")))
    if not files:
        return None
    try:
        return NativeTiktokenTokenizer(path, files[0])
    except Exception:
        return None
