"""Tokenization tier.

The reference ships three native tokenizer families behind one interface —
a Rust HF-tokenizers FFI crate, sentencepiece, and a tiktoken BPE
(reference: xllm_service/tokenizer/tokenizer.h:28-46,
tokenizer_factory.cpp:9-33, fast_tokenizer.cpp, sentencepiece_tokenizer.cpp,
tiktoken_tokenizer.cpp). On this stack TWO native families cover the
dominant formats — the C++ byte-level BPE core (tokenizer/native_bpe.py,
GPT-2/Llama-3/Qwen style) and the C++ SentencePiece-Unigram core
(tokenizer/native_sp.py, .model protobuf + Viterbi + byte fallback) —
with `transformers.AutoTokenizer` (the same Rust `tokenizers` wheel the
reference binds by hand) as the fallback adapter for everything else; a
deterministic byte-level tokenizer covers tests and benches with no model
files on disk.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Sequence


class Tokenizer:
    """Interface (reference: tokenizer.h:28-46)."""

    def encode(self, text: str) -> List[int]:
        raise NotImplementedError

    def decode(self, ids: Sequence[int], skip_special_tokens: bool = True) -> str:
        raise NotImplementedError

    def id_to_token(self, token_id: int) -> str:
        raise NotImplementedError

    def token_to_id(self, token: str) -> Optional[int]:
        raise NotImplementedError

    @property
    def vocab_size(self) -> int:
        raise NotImplementedError

    @property
    def eos_token_id(self) -> Optional[int]:
        return None

    @property
    def bos_token_id(self) -> Optional[int]:
        return None

    def token_bytes_table(self, vocab_size: int) -> "Optional[List[bytes]]":
        """Per-id raw bytes for guided decoding (JSON mode). None =
        unsupported for this tokenizer family (guided requests are then
        rejected with a clear error). Ids with no byte surface (specials,
        out-of-table) map to b""."""
        return None


class ByteTokenizer(Tokenizer):
    """UTF-8 byte-level tokenizer: id = byte + 3 (0=pad, 1=bos, 2=eos).

    Deterministic, file-free; the test/bench stand-in for a real model
    tokenizer (SURVEY.md §4: the reference has no such seam and cannot unit
    test its tokenize path without model dirs on disk)."""

    PAD, BOS, EOS = 0, 1, 2
    _OFFSET = 3

    def encode(self, text: str) -> List[int]:
        return [b + self._OFFSET for b in text.encode("utf-8")]

    def decode(self, ids: Sequence[int], skip_special_tokens: bool = True) -> str:
        # Total over arbitrary ids: a model whose vocab exceeds 259 (e.g. the
        # random-init test models) may emit any id — fold it onto a byte.
        data = bytes(
            (i - self._OFFSET) % 256 for i in ids if i >= self._OFFSET
        )
        return data.decode("utf-8", errors="replace")

    def id_to_token(self, token_id: int) -> str:
        if 0 <= token_id < self._OFFSET:
            return ["<pad>", "<bos>", "<eos>"][token_id]
        return chr((token_id - self._OFFSET) % 256)

    def token_to_id(self, token: str) -> Optional[int]:
        specials = {"<pad>": 0, "<bos>": 1, "<eos>": 2}
        if token in specials:
            return specials[token]
        b = token.encode("utf-8")
        return b[0] + self._OFFSET if len(b) == 1 else None

    @property
    def vocab_size(self) -> int:
        return 256 + self._OFFSET

    @property
    def eos_token_id(self) -> Optional[int]:
        return self.EOS

    @property
    def bos_token_id(self) -> Optional[int]:
        return self.BOS

    def token_bytes_table(self, vocab_size: int) -> "List[bytes]":
        # model vocabs may exceed 259 (random-init test configs): decode
        # folds id onto (id - 3) % 256, so the byte table does too
        out = [b"" for _ in range(vocab_size)]
        for i in range(self._OFFSET, vocab_size):
            out[i] = bytes([(i - self._OFFSET) % 256])
        return out


class HFTokenizer(Tokenizer):
    """Adapter over transformers.AutoTokenizer — the union of the
    reference's Fast (tokenizer.json), SentencePiece, and Tiktoken families.
    Encode/decode on HF fast tokenizers is thread-safe; the slow (Python)
    path is guarded by a lock, replacing the reference's thread-local clones
    (scheduler.cpp:166-169)."""

    def __init__(self, path: str):
        from transformers import AutoTokenizer

        self._tok = AutoTokenizer.from_pretrained(path, trust_remote_code=False)
        self._lock = threading.Lock() if not self._tok.is_fast else None

    def _guarded(self, fn):
        if self._lock is None:
            return fn()
        with self._lock:
            return fn()

    def encode(self, text: str) -> List[int]:
        return self._guarded(lambda: self._tok.encode(text, add_special_tokens=False))

    def decode(self, ids: Sequence[int], skip_special_tokens: bool = True) -> str:
        return self._guarded(
            lambda: self._tok.decode(list(ids), skip_special_tokens=skip_special_tokens)
        )

    def id_to_token(self, token_id: int) -> str:
        return self._guarded(lambda: self._tok.convert_ids_to_tokens(token_id)) or ""

    def token_to_id(self, token: str) -> Optional[int]:
        tid = self._guarded(lambda: self._tok.convert_tokens_to_ids(token))
        return None if tid == self._tok.unk_token_id and token != self._tok.unk_token else tid

    @property
    def vocab_size(self) -> int:
        return len(self._tok)

    @property
    def eos_token_id(self) -> Optional[int]:
        return self._tok.eos_token_id

    @property
    def bos_token_id(self) -> Optional[int]:
        return self._tok.bos_token_id

    @property
    def hf(self):
        return self._tok

    def token_bytes_table(self, vocab_size: int) -> "Optional[List[bytes]]":
        """Byte surfaces via the tokenizer's own convention: GPT-2-style
        byte-level vocabs map through the bytes_to_unicode table;
        SentencePiece pieces map '\u2581' to space and '<0xNN>' byte
        tokens to their byte; specials map to b""."""
        # GPT-2 byte-level unicode->byte inverse table
        bs = (
            list(range(0x21, 0x7F)) + list(range(0xA1, 0xAD))
            + list(range(0xAE, 0x100))
        )
        cs = bs[:]
        n = 0
        for b in range(256):
            if b not in bs:
                bs.append(b)
                cs.append(256 + n)
                n += 1
        uni2byte = {chr(c): b for b, c in zip(bs, cs)}

        special = set(self._tok.all_special_ids or [])
        toks = self._guarded(
            lambda: self._tok.convert_ids_to_tokens(
                list(range(min(vocab_size, len(self._tok))))
            )
        )
        out: List[bytes] = []
        for tid, t in enumerate(toks):
            if t is None or tid in special:
                out.append(b"")
                continue
            if t.startswith("<0x") and t.endswith(">") and len(t) == 6:
                try:
                    out.append(bytes([int(t[3:5], 16)]))
                    continue
                except ValueError:
                    pass
            if all(ch in uni2byte for ch in t):
                out.append(bytes(uni2byte[ch] for ch in t))
            else:
                out.append(t.replace("▁", " ").encode("utf-8"))
        out += [b""] * (vocab_size - len(out))
        return out


class IncrementalDetokenizer:
    """Streaming-safe detokenization for one sequence.

    Decoding each step's token ids independently corrupts characters whose
    bytes span token boundaries (routine for byte-level and BPE
    byte-fallback vocabularies). This keeps the full id history, re-decodes,
    and emits only the newly *stable* text — a trailing run of U+FFFD
    replacement chars is held back until later tokens complete the
    sequence (vLLM-style prefix-diff detokenization)."""

    def __init__(self, tokenizer: Tokenizer):
        self._tok = tokenizer
        self._ids: List[int] = []
        self._emitted = 0

    def push(self, ids: Sequence[int]) -> str:
        self._ids.extend(int(i) for i in ids)
        text = self._tok.decode(self._ids)
        stable_end = len(text)
        while stable_end > self._emitted and text[stable_end - 1] == "�":
            stable_end -= 1
        delta = text[self._emitted:stable_end]
        self._emitted = stable_end
        return delta

    def flush(self) -> str:
        """Emit whatever is still held back (end of stream)."""
        text = self._tok.decode(self._ids)
        delta = text[self._emitted:]
        self._emitted = len(text)
        return delta

    # State carry-over across a PD handoff: the decode peer must continue
    # the prefill peer's byte/char position or the streamed text diverges
    # from a colocated run.
    def export_state(self) -> "tuple[List[int], int]":
        return list(self._ids), self._emitted

    @classmethod
    def from_state(
        cls, tokenizer: Tokenizer, ids: Sequence[int], emitted: int
    ) -> "IncrementalDetokenizer":
        d = cls(tokenizer)
        d._ids = [int(i) for i in ids]
        d._emitted = int(emitted)
        return d


def create_tokenizer(path: str = "") -> Tokenizer:
    """Factory (reference: tokenizer_factory.cpp:9-33). Empty path selects
    the byte tokenizer (tests/bench). A model dir first tries the NATIVE
    byte-level BPE family (C++ core, tokenizer/native_bpe.py — the
    reference's native-tokenizer analog); models outside that family
    (SentencePiece, exotic normalizers) and hub ids fall back to
    transformers. XLLM_NATIVE_TOKENIZER=0 forces the HF path."""
    import os

    if not path or path == "byte":
        return ByteTokenizer()
    if os.path.isdir(path) and os.environ.get("XLLM_NATIVE_TOKENIZER") != "0":
        from xllm_service_tpu.tokenizer import (
            native_bpe,
            native_sp,
            native_tiktoken,
        )

        tok = native_bpe.try_load(path)
        if tok is not None:
            return tok
        # SentencePiece family (.model protobuf, Unigram + byte fallback)
        # — the reference's sentencepiece_tokenizer.cpp analog.
        sp = native_sp.try_load(path)
        if sp is not None:
            return sp
        # Tiktoken family (*.tiktoken base64 vocab, rank merges) — the
        # reference's tiktoken_tokenizer.cpp analog.
        tk = native_tiktoken.try_load(path)
        if tk is not None:
            return tk
    return HFTokenizer(path)
