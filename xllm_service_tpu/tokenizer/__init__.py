"""Tokenization + chat templating (reference: xllm_service/tokenizer/, chat_template/)."""

from xllm_service_tpu.tokenizer.chat_template import (
    ChatTemplate,
    Message,
    MMContentPart,
    parse_messages,
)
from xllm_service_tpu.tokenizer.tokenizer import (
    ByteTokenizer,
    HFTokenizer,
    Tokenizer,
    create_tokenizer,
)

__all__ = [
    "ChatTemplate",
    "Message",
    "MMContentPart",
    "parse_messages",
    "ByteTokenizer",
    "HFTokenizer",
    "Tokenizer",
    "create_tokenizer",
]
