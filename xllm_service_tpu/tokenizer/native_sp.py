"""Native SentencePiece-Unigram tokenizer family.

The reference implements a sentencepiece tokenizer natively
(xllm_service/tokenizer/sentencepiece_tokenizer.{h,cpp} over the vendored
sentencepiece C++ library, selected by tokenizer_factory.cpp when the
model dir carries a .model file). This is the rebuild's native family for
that path: `native/sp_tokenizer.cpp` parses the .model protobuf itself
(ModelProto wire format) and runs Viterbi Unigram segmentation with byte
fallback behind a ctypes C ABI; this wrapper handles file discovery,
special-token config, and the Tokenizer interface.

Scope: Unigram models with the standard normalizer flags. Models whose
normalizer carries a precompiled charsmap (NFKC etc.) are declined —
`try_load` returns None and the factory falls back to the transformers
adapter (correctness over coverage, same policy as native_bpe).
"""

from __future__ import annotations

import ctypes
import functools
import json
import os
from typing import List, Optional, Sequence

from xllm_service_tpu.tokenizer._native_build import (
    build_and_load,
    named_token_str,
)
from xllm_service_tpu.tokenizer.tokenizer import Tokenizer

_NATIVE_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "native"
)
_SRC = os.path.join(_NATIVE_DIR, "sp_tokenizer.cpp")
_LIB = os.path.join(_NATIVE_DIR, "libxllm_sp.so")

@functools.lru_cache(maxsize=1)
def _load_lib() -> Optional[ctypes.CDLL]:
    lib = build_and_load(_SRC, _LIB)
    if lib is None:
        return None
    lib.sp_create.restype = ctypes.c_void_p
    lib.sp_create.argtypes = [ctypes.c_char_p, ctypes.c_int64]
    lib.sp_destroy.argtypes = [ctypes.c_void_p]
    lib.sp_vocab_size.argtypes = [ctypes.c_void_p]
    lib.sp_vocab_size.restype = ctypes.c_int
    lib.sp_has_charsmap.argtypes = [ctypes.c_void_p]
    lib.sp_has_charsmap.restype = ctypes.c_int
    lib.sp_unk_id.argtypes = [ctypes.c_void_p]
    lib.sp_unk_id.restype = ctypes.c_int
    lib.sp_encode.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int64,
        ctypes.POINTER(ctypes.c_int32), ctypes.c_int,
    ]
    lib.sp_encode.restype = ctypes.c_int
    lib.sp_decode.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_int32), ctypes.c_int,
        ctypes.c_char_p, ctypes.c_int,
    ]
    lib.sp_decode.restype = ctypes.c_int
    lib.sp_piece_to_id.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.sp_piece_to_id.restype = ctypes.c_int
    lib.sp_id_to_piece.argtypes = [
        ctypes.c_void_p, ctypes.c_int, ctypes.c_char_p, ctypes.c_int
    ]
    lib.sp_id_to_piece.restype = ctypes.c_int
    lib.sp_piece_type.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.sp_piece_type.restype = ctypes.c_int
    return lib


_MODEL_NAMES = ("tokenizer.model", "spiece.model", "spm.model")


class NativeSPTokenizer(Tokenizer):
    """SentencePiece Unigram over the native core (one instance per model
    dir; the C handle is owned here and freed on GC)."""

    def __init__(self, path: str, model_file: str):
        lib = _load_lib()
        assert lib is not None
        self._lib = lib
        with open(model_file, "rb") as f:
            blob = f.read()
        self._h = lib.sp_create(blob, len(blob))
        if not self._h:
            raise ValueError(f"{model_file}: not a sentencepiece model")
        self._vocab = lib.sp_vocab_size(self._h)
        self._unk = lib.sp_unk_id(self._h)

        # Special-token strings + chat template from tokenizer_config.json
        # (same contract native_bpe reads; CONTROL pieces <s>/</s> are the
        # usual fallback names).
        self.bos_token: Optional[str] = None
        self.eos_token: Optional[str] = None
        self.chat_template: Optional[str] = None
        cfg_path = os.path.join(path, "tokenizer_config.json")
        if os.path.isfile(cfg_path):
            with open(cfg_path, encoding="utf-8") as f:
                cfg = json.load(f)
            self.bos_token = named_token_str(cfg.get("bos_token"))
            self.eos_token = named_token_str(cfg.get("eos_token"))
            ct = cfg.get("chat_template")
            if isinstance(ct, str):
                self.chat_template = ct
        if self.bos_token is None and self.token_to_id("<s>") is not None:
            self.bos_token = "<s>"
        if self.eos_token is None and self.token_to_id("</s>") is not None:
            self.eos_token = "</s>"

        # Special-token surface forms never match inside Viterbi (CONTROL
        # pieces are excluded from segmentation, exactly like real
        # sentencepiece) — chat templates INJECT them as text ("<s>",
        # "<|eot_id|>" ...), so encode() splits on them first and emits
        # their ids directly (native_bpe's added-token splitting, the HF
        # added_tokens contract). Sources: every CONTROL/unused piece in
        # the model + added_tokens_decoder entries in tokenizer_config.
        specials: dict = {}
        buf = ctypes.create_string_buffer(512)
        for i in range(self._vocab):
            t = lib.sp_piece_type(self._h, i)
            if t in (3, 5):  # CONTROL / UNUSED
                n = lib.sp_id_to_piece(self._h, i, buf, 512)
                if n > 0:
                    specials[buf.raw[:n].decode("utf-8", "replace")] = i
        if os.path.isfile(cfg_path):
            for spec in (cfg.get("added_tokens_decoder") or {}).values():
                s = named_token_str(spec)
                sid = (
                    self.token_to_id(s) if isinstance(s, str) else None
                )
                if s and sid is not None:
                    specials[s] = sid
        self._specials = specials
        self._special_re = None
        if specials:
            import re

            self._special_re = re.compile(
                "|".join(
                    re.escape(s)
                    for s in sorted(specials, key=len, reverse=True)
                )
            )

    def __del__(self):
        h = getattr(self, "_h", None)
        if h:
            self._lib.sp_destroy(h)
            self._h = None

    # ------------------------------------------------------------- encode
    def _encode_plain(self, text: str) -> List[int]:
        data = text.encode("utf-8")
        cap = max(16, len(data) * 2)
        while True:
            buf = (ctypes.c_int32 * cap)()
            n = self._lib.sp_encode(self._h, data, len(data), buf, cap)
            if n == -(2**31):
                raise ValueError("sentencepiece encode failed")
            if n < 0:
                cap = -n
                continue
            return list(buf[:n])

    def encode(self, text: str) -> List[int]:
        if self._special_re is None:
            return self._encode_plain(text)
        # Split on special-token surface forms; each plain segment goes
        # through the native core independently (the dummy prefix applies
        # per segment — HF's sentencepiece added-token behavior).
        out: List[int] = []
        pos = 0
        for m in self._special_re.finditer(text):
            if m.start() > pos:
                out.extend(self._encode_plain(text[pos:m.start()]))
            out.append(self._specials[m.group(0)])
            pos = m.end()
        if pos < len(text):
            out.extend(self._encode_plain(text[pos:]))
        return out

    def decode(self, ids: Sequence[int], skip_special_tokens: bool = True) -> str:
        arr = (ctypes.c_int32 * len(ids))(*[int(i) for i in ids])
        cap = max(16, len(ids) * 8)
        while True:
            out = ctypes.create_string_buffer(cap)
            n = self._lib.sp_decode(self._h, arr, len(ids), out, cap)
            if n < 0:
                cap = -n
                continue
            return out.raw[:n].decode("utf-8", errors="replace")

    def id_to_token(self, token_id: int) -> str:
        out = ctypes.create_string_buffer(256)
        n = self._lib.sp_id_to_piece(self._h, int(token_id), out, 256)
        return out.raw[:n].decode("utf-8", errors="replace") if n >= 0 else ""

    def token_to_id(self, token: str) -> Optional[int]:
        i = self._lib.sp_piece_to_id(self._h, token.encode("utf-8"))
        return None if i < 0 else i

    @property
    def vocab_size(self) -> int:
        return self._vocab

    @property
    def bos_token_id(self) -> Optional[int]:
        return self.token_to_id(self.bos_token) if self.bos_token else None

    @property
    def eos_token_id(self) -> Optional[int]:
        return self.token_to_id(self.eos_token) if self.eos_token else None


def try_load(path: str) -> Optional[NativeSPTokenizer]:
    """A NativeSPTokenizer for this model dir, or None when there is no
    .model file, the native lib can't build, or the model needs charsmap
    normalization (NFKC) we don't implement — the factory then falls back
    to the transformers adapter."""
    lib = _load_lib()
    if lib is None:
        return None
    model_file = next(
        (
            os.path.join(path, n)
            for n in _MODEL_NAMES
            if os.path.isfile(os.path.join(path, n))
        ),
        None,
    )
    if model_file is None:
        return None
    try:
        tok = NativeSPTokenizer(path, model_file)
    except Exception:
        return None
    if lib.sp_has_charsmap(tok._h):
        return None
    return tok
