"""Shared build-and-load for the native tokenizer cores.

All three families (native_bpe / native_sp / native_tiktoken) self-compile
their C++ core on first use with a staleness check; the pipeline lives
here ONCE so compiler flags and the stale-.so handling can't drift."""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

_build_lock = threading.Lock()


def build_and_load(src: str, lib_path: str) -> Optional[ctypes.CDLL]:
    """Compile `src` to `lib_path` when missing/stale and dlopen it;
    None when the toolchain or load fails (callers fall back to the
    transformers adapter)."""
    with _build_lock:
        try:
            if not os.path.exists(lib_path) or os.path.getmtime(
                src
            ) > os.path.getmtime(lib_path):
                subprocess.run(
                    [
                        "g++", "-O2", "-shared", "-fPIC", "-std=c++17",
                        src, "-o", lib_path,
                    ],
                    check=True, capture_output=True,
                )
            return ctypes.CDLL(lib_path)
        except Exception:
            return None


def named_token_str(v) -> Optional[str]:
    """tokenizer_config.json token specs are either plain strings or
    {"content": ...} dicts."""
    if isinstance(v, str):
        return v
    if isinstance(v, dict):
        return v.get("content")
    return None
