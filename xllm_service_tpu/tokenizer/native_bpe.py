"""Native byte-level BPE tokenizer: C++ merge core + Python model parsing.

The reference implements its tokenizer families natively (Rust
HF-tokenizers FFI, sentencepiece_tokenizer.cpp, tiktoken_tokenizer.cpp —
reference xllm_service/tokenizer/). This is the rebuild's native family:
`native/bpe_tokenizer.cpp` owns the hot path (BPE merge loop, vocab
tables, word cache) behind a ctypes C ABI; this wrapper parses the HF
`tokenizer.json` model, runs the unicode regex pre-tokenization (the
`regex` module speaks \\p{L} classes; std::regex does not), and handles
added/special tokens.

Coverage: BPE models with ByteLevel pre-tokenization (GPT-2/Llama-3/Qwen
style — the dominant modern family). `try_load` returns None for anything
else (SentencePiece-Unigram models, normalizers beyond NFC/NFKC,
add_prefix_space) and the factory falls back to transformers — correctness
over coverage, parity-tested against HF on a real tokenizer dir.
"""

from __future__ import annotations

import ctypes
import functools
import json
import os
import unicodedata
from typing import Dict, List, Optional, Sequence

import regex as _regex

from xllm_service_tpu.tokenizer.tokenizer import Tokenizer

_NATIVE_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "native"
)
_SRC = os.path.join(_NATIVE_DIR, "bpe_tokenizer.cpp")
_LIB = os.path.join(_NATIVE_DIR, "libxllm_bpe.so")

# GPT-2 ByteLevel pre-tokenization pattern (the default HF ByteLevel
# regex); Llama-3-style tokenizers override it via a Split pre-tokenizer
# whose pattern we read from tokenizer.json.
_GPT2_PAT = (
    r"'s|'t|'re|'ve|'m|'ll|'d| ?\p{L}+| ?\p{N}+| ?[^\s\p{L}\p{N}]+"
    r"|\s+(?!\S)|\s+"
)

@functools.lru_cache(maxsize=1)
def _load_lib() -> Optional[ctypes.CDLL]:
    from xllm_service_tpu.tokenizer._native_build import build_and_load

    lib = build_and_load(_SRC, _LIB)
    if lib is None:
        return None
    P, I, C = ctypes.c_void_p, ctypes.c_int32, ctypes.c_char_p
    IP = ctypes.POINTER(ctypes.c_int32)
    lib.xbpe_new.restype = P
    lib.xbpe_new.argtypes = [I]
    lib.xbpe_free.argtypes = [P]
    lib.xbpe_set_token.argtypes = [P, I, C, I]
    lib.xbpe_set_token.restype = I
    lib.xbpe_set_byte_token.argtypes = [P, I, I]
    lib.xbpe_add_merge.argtypes = [P, I, I, I, I]
    lib.xbpe_encode_word.argtypes = [P, C, I, IP, I]
    lib.xbpe_encode_word.restype = I
    lib.xbpe_decode.argtypes = [P, IP, I, C, I]
    lib.xbpe_decode.restype = I
    return lib


@functools.lru_cache(maxsize=1)
def _unicode_to_byte() -> Dict[str, int]:
    return {c: b for b, c in _byte_to_unicode().items()}


@functools.lru_cache(maxsize=1)
def _byte_to_unicode() -> Dict[int, str]:
    """GPT-2 byte<->unicode alphabet (printable stand-ins for raw bytes)."""
    bs = (
        list(range(ord("!"), ord("~") + 1))
        + list(range(ord("\xa1"), ord("\xac") + 1))
        + list(range(ord("\xae"), ord("\xff") + 1))
    )
    cs = bs[:]
    n = 0
    for b in range(256):
        if b not in bs:
            bs.append(b)
            cs.append(256 + n)
            n += 1
    return dict(zip(bs, (chr(c) for c in cs)))


def _token_str_to_bytes(s: str) -> Optional[bytes]:
    u2b = _unicode_to_byte()  # cached — called once per vocab entry
    out = bytearray()
    for ch in s:
        b = u2b.get(ch)
        if b is None:
            return None  # not a byte-level token
        out.append(b)
    return bytes(out)


def _bytes_to_token_str(raw: bytes) -> str:
    b2u = _byte_to_unicode()
    return "".join(b2u[b] for b in raw)


class NativeBPETokenizer(Tokenizer):
    """HF-compatible byte-level BPE over the native C++ core."""

    def __init__(self, path: str, model: dict):
        lib = _load_lib()
        assert lib is not None
        self._lib = lib
        vocab: Dict[str, int] = model["model"]["vocab"]
        merges = model["model"]["merges"]
        added = model.get("added_tokens") or []

        self._token_to_id: Dict[str, int] = dict(vocab)
        n_ids = max(
            [max(vocab.values(), default=-1)]
            + [t["id"] for t in added]
        ) + 1
        self._id_to_token: List[str] = [""] * n_ids
        for tok, tid in vocab.items():
            self._id_to_token[tid] = tok

        self._bpe = lib.xbpe_new(n_ids)
        b2u = _byte_to_unicode()
        for tok, tid in vocab.items():
            raw = _token_str_to_bytes(tok)
            if raw is None:
                raw = tok.encode("utf-8")  # non-byte-level (added) entry
            lib.xbpe_set_token(self._bpe, tid, raw, len(raw))
        for byte, ch in b2u.items():
            tid = vocab.get(ch)
            if tid is not None:
                lib.xbpe_set_byte_token(self._bpe, byte, tid)
        for rank, m in enumerate(merges):
            left, right = m if isinstance(m, (list, tuple)) else m.split(" ", 1)
            li, ri, mi = (
                vocab.get(left), vocab.get(right), vocab.get(left + right)
            )
            if li is not None and ri is not None and mi is not None:
                lib.xbpe_add_merge(self._bpe, li, ri, mi, rank)

        # Added/special tokens: matched verbatim before BPE.
        self._special_ids = set()
        self._added: List[str] = []
        for t in added:
            self._token_to_id[t["content"]] = t["id"]
            if t["id"] < n_ids:
                self._id_to_token[t["id"]] = t["content"]
                raw = t["content"].encode("utf-8")
                lib.xbpe_set_token(self._bpe, t["id"], raw, len(raw))
            self._added.append(t["content"])
            if t.get("special"):
                self._special_ids.add(t["id"])
        self._added.sort(key=len, reverse=True)
        self._added_re = (
            _regex.compile(
                "(" + "|".join(_regex.escape(t) for t in self._added) + ")"
            )
            if self._added
            else None
        )

        self._pat = _regex.compile(self._split_pattern(model))
        self._normalizer = self._normalizer_form(model)
        # Llama-3-style BPE: whole pre-tokenized words that exist in the
        # vocab bypass the merge loop (the converted merge list cannot
        # reconstruct every whole-word entry).
        self._ignore_merges = bool(model["model"].get("ignore_merges"))

        # bos/eos + chat template from tokenizer_config.json. The token
        # STRINGS are kept too — chat templates reference {{ bos_token }} /
        # {{ eos_token }} directly.
        self._eos_id: Optional[int] = None
        self._bos_id: Optional[int] = None
        self.eos_token: Optional[str] = None
        self.bos_token: Optional[str] = None
        cfg_path = os.path.join(path, "tokenizer_config.json")
        self.chat_template: Optional[str] = None
        if os.path.exists(cfg_path):
            with open(cfg_path) as f:
                cfg = json.load(f)
            self.eos_token = self._named_token_str(cfg.get("eos_token"))
            self.bos_token = self._named_token_str(cfg.get("bos_token"))
            self._eos_id = self._named_token_id(cfg.get("eos_token"))
            self._bos_id = self._named_token_id(cfg.get("bos_token"))
            ct = cfg.get("chat_template")
            if isinstance(ct, str):
                self.chat_template = ct

    def __del__(self):
        bpe, self._bpe = getattr(self, "_bpe", None), None
        if bpe and getattr(self, "_lib", None):
            self._lib.xbpe_free(bpe)

    # ------------------------------------------------------------- parsing

    @staticmethod
    def supported(model: dict) -> bool:
        m = model.get("model") or {}
        if m.get("type") != "BPE":
            return False
        if NativeBPETokenizer._normalizer_form(model) is False:
            return False
        return NativeBPETokenizer._split_pattern(model) is not None

    @staticmethod
    def _normalizer_form(model: dict):
        """None (no-op), an NFC/NFKC form name, or False (unsupported)."""
        nz = model.get("normalizer")
        if nz is None:
            return None
        if nz.get("type") in ("NFC", "NFKC"):
            return nz["type"]
        return False

    @staticmethod
    def _split_pattern(model: dict) -> Optional[str]:
        """The pre-tokenization regex, or None when unsupported."""
        pt = model.get("pre_tokenizer")
        if pt is None:
            return None

        def from_one(p) -> Optional[str]:
            if p.get("type") == "ByteLevel":
                if p.get("add_prefix_space"):
                    return None  # changes text; fall back to HF
                return _GPT2_PAT if p.get("use_regex", True) else ""
            if p.get("type") == "Split":
                pat = p.get("pattern") or {}
                if "Regex" in pat and p.get("behavior") == "Isolated":
                    return pat["Regex"]
                return None
            return None

        if pt.get("type") == "Sequence":
            pats = [from_one(p) for p in pt.get("pretokenizers", [])]
            if any(p is None for p in pats):
                return None
            real = [p for p in pats if p]
            return real[0] if len(real) == 1 else (None if real else "")
        return from_one(pt)

    @staticmethod
    def _named_token_str(tok) -> Optional[str]:
        if isinstance(tok, dict):
            tok = tok.get("content")
        return tok if isinstance(tok, str) else None

    def _named_token_id(self, tok) -> Optional[int]:
        tok = self._named_token_str(tok)
        return self._token_to_id.get(tok) if tok is not None else None

    # ------------------------------------------------------------ interface

    def _pretokenize(self, seg: str) -> List[str]:
        """Isolated-split semantics: matched spans AND the gaps between
        them (a Split regex need not cover every character — HF keeps
        unmatched spans as their own segments; findall would drop them,
        and would return groups for patterns with capture groups)."""
        if not self._pat.pattern:
            return [seg]
        words: List[str] = []
        pos = 0
        for m in self._pat.finditer(seg):
            if m.start() > pos:
                words.append(seg[pos:m.start()])
            if m.group(0):
                words.append(m.group(0))
            pos = m.end()
        if pos < len(seg):
            words.append(seg[pos:])
        return words

    def encode(self, text: str) -> List[int]:
        if self._normalizer:
            text = unicodedata.normalize(self._normalizer, text)
        out: List[int] = []
        segments = (
            self._added_re.split(text) if self._added_re else [text]
        )
        buf = (ctypes.c_int32 * 512)()
        for i, seg in enumerate(segments):
            if not seg:
                continue
            if i % 2 == 1:  # added-token capture group
                out.append(self._token_to_id[seg])
                continue
            for word in self._pretokenize(seg):
                raw = word.encode("utf-8")
                if self._ignore_merges:
                    whole = self._token_to_id.get(_bytes_to_token_str(raw))
                    if whole is not None:
                        out.append(whole)
                        continue
                n = self._lib.xbpe_encode_word(
                    self._bpe, raw, len(raw), buf, len(buf)
                )
                if n > len(buf):
                    big = (ctypes.c_int32 * n)()
                    self._lib.xbpe_encode_word(
                        self._bpe, raw, len(raw), big, n
                    )
                    out.extend(big[:n])
                else:
                    out.extend(buf[:n])
        return out

    def decode(self, ids: Sequence[int], skip_special_tokens: bool = True) -> str:
        ids = [
            i
            for i in ids
            if not (skip_special_tokens and i in self._special_ids)
        ]
        arr = (ctypes.c_int32 * max(len(ids), 1))(*ids)
        cap = 16 + 8 * len(ids)
        buf = ctypes.create_string_buffer(cap)
        n = self._lib.xbpe_decode(self._bpe, arr, len(ids), buf, cap)
        if n > cap:
            buf = ctypes.create_string_buffer(n)
            self._lib.xbpe_decode(self._bpe, arr, len(ids), buf, n)
        return buf.raw[:n].decode("utf-8", errors="replace")

    def id_to_token(self, token_id: int) -> str:
        if 0 <= token_id < len(self._id_to_token):
            return self._id_to_token[token_id]
        return ""

    def token_to_id(self, token: str) -> Optional[int]:
        return self._token_to_id.get(token)

    @property
    def vocab_size(self) -> int:
        return len(self._id_to_token)

    @property
    def eos_token_id(self) -> Optional[int]:
        return self._eos_id

    @property
    def bos_token_id(self) -> Optional[int]:
        return self._bos_id


def try_load(path: str) -> Optional[NativeBPETokenizer]:
    """A NativeBPETokenizer for this model dir, or None when the model is
    outside the supported family / the native lib can't build."""
    tj = os.path.join(path, "tokenizer.json")
    if not os.path.isfile(tj) or _load_lib() is None:
        return None
    try:
        with open(tj, encoding="utf-8") as f:
            model = json.load(f)
        if not NativeBPETokenizer.supported(model):
            return None
        return NativeBPETokenizer(path, model)
    except Exception:
        return None
