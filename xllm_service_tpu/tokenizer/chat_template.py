"""Chat templating with tool-call and multimodal content support.

The reference renders HF Jinja chat templates through the minja C++ engine
with a multimodal message model and tool/function JSON
(reference: xllm_service/chat_template/jinja_chat_template.{h,cpp}:
Message/MMContent h:30-61, apply() cpp:53-99, mm placeholder serialization
cpp:101-120). Here the real Jinja path is the tokenizer's own
`apply_chat_template` (same template source: the model dir's
tokenizer_config.json / chat_template.jinja), with a deterministic fallback
template for tokenizer-less runs.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Union

from xllm_service_tpu.tokenizer.tokenizer import HFTokenizer, Tokenizer


class TemplateReject(ValueError):
    """Raised when a chat template's own raise_exception() rejects the
    conversation (e.g. role-alternation checks) — a client error, never
    swallowed by the render-failure fallback."""


@dataclass
class MMContentPart:
    """One multimodal content part (reference: MMContent,
    jinja_chat_template.h:30-47): type in
    {text, image_url, video_url, audio_url}."""

    type: str = "text"
    text: str = ""
    url: str = ""

    @classmethod
    def from_json(cls, j: Dict[str, Any]) -> "MMContentPart":
        t = j.get("type", "text")
        if t == "text":
            return cls(type="text", text=j.get("text", ""))
        payload = j.get(t) or {}
        return cls(type=t, url=payload.get("url", "") if isinstance(payload, dict) else str(payload))

    def to_json(self) -> Dict[str, Any]:
        if self.type == "text":
            return {"type": "text", "text": self.text}
        return {"type": self.type, self.type: {"url": self.url}}


@dataclass
class Message:
    """Chat message; content is either a plain string or multimodal parts
    (reference: Message, jinja_chat_template.h:49-61)."""

    role: str = "user"
    content: Union[str, List[MMContentPart]] = ""

    @classmethod
    def from_json(cls, j: Dict[str, Any]) -> "Message":
        content = j.get("content", "")
        if isinstance(content, list):
            return cls(
                role=j.get("role", "user"),
                content=[MMContentPart.from_json(p) for p in content],
            )
        return cls(role=j.get("role", "user"), content=content or "")

    def flat_text(self) -> str:
        """Serialize multimodal parts to text with media placeholders
        (reference: mm placeholder serialization, cpp:101-120)."""
        if isinstance(self.content, str):
            return self.content
        parts = []
        for p in self.content:
            if p.type == "text":
                parts.append(p.text)
            else:
                # <|image|> / <|video|> / <|audio|> markers the encoder
                # stage later resolves against the request's media inputs.
                marker = p.type.split("_")[0]
                parts.append(f"<|{marker}|>")
        return "".join(parts)

    def to_hf(self) -> Dict[str, Any]:
        return {"role": self.role, "content": self.flat_text()}


class ChatTemplate:
    """apply(messages, tools) -> prompt string
    (reference: JinjaChatTemplate::apply, jinja_chat_template.cpp:53-99)."""

    def __init__(self, tokenizer: Optional[Tokenizer] = None):
        self._hf = tokenizer.hf if isinstance(tokenizer, HFTokenizer) else None
        # Native tokenizers carry the model dir's raw Jinja template string
        # (tokenizer_config.json chat_template) — compiled ONCE here (the
        # apply() below runs on the request hot path) and rendered with the
        # same context HF's apply_chat_template provides: special-token
        # strings and raise_exception (stock templates use both).
        self._compiled = None
        self._render_warned = False
        self._special_ctx: Dict[str, Any] = {}
        template = getattr(tokenizer, "chat_template", None)
        if template and self._hf is None:
            import jinja2

            def raise_exception(message):
                raise TemplateReject(message)

            env = jinja2.Environment(
                trim_blocks=True, lstrip_blocks=True,
                extensions=["jinja2.ext.loopcontrols"],
            )
            env.globals["raise_exception"] = raise_exception

            def strftime_now(fmt):
                # Stock Llama-3.1/3.2-Instruct templates call
                # strftime_now("%d %b %Y") for date_string; HF injects the
                # same callable into apply_chat_template's environment.
                import datetime

                return datetime.datetime.now().strftime(fmt)

            env.globals["strftime_now"] = strftime_now
            self._compiled = env.from_string(template)
            self._special_ctx = {
                "bos_token": getattr(tokenizer, "bos_token", None) or "",
                "eos_token": getattr(tokenizer, "eos_token", None) or "",
            }

    def apply(
        self,
        messages: List[Message],
        tools: Optional[List[Dict[str, Any]]] = None,
    ) -> str:
        if self._hf is not None and getattr(self._hf, "chat_template", None):
            return self._hf.apply_chat_template(
                [m.to_hf() for m in messages],
                tools=tools,
                tokenize=False,
                add_generation_prompt=True,
            )
        if self._compiled is not None:
            try:
                return self._compiled.render(
                    messages=[m.to_hf() for m in messages],
                    tools=tools,
                    add_generation_prompt=True,
                    **self._special_ctx,
                )
            except TemplateReject:
                # The template itself rejected the conversation via
                # raise_exception (e.g. role-alternation checks) — a real
                # client error that must fail the request, same as the HF
                # path would.
                raise
            except Exception as e:
                # A template referencing a global we don't provide must not
                # fail the request — degrade to the deterministic template,
                # loudly (once) so silent format corruption is diagnosable.
                if not self._render_warned:
                    self._render_warned = True
                    import logging

                    logging.getLogger(__name__).warning(
                        "chat template render failed (%s: %s); falling back "
                        "to the ChatML template for this tokenizer",
                        type(e).__name__, e,
                    )
        return self._fallback(messages, tools)

    @staticmethod
    def _fallback(
        messages: List[Message], tools: Optional[List[Dict[str, Any]]]
    ) -> str:
        """ChatML-shaped deterministic template for tokenizer-less runs."""
        import json as _json

        out = []
        if tools:
            out.append(
                "<|im_start|>system\n# Tools\n"
                + _json.dumps(tools, sort_keys=True)
                + "<|im_end|>\n"
            )
        for m in messages:
            out.append(f"<|im_start|>{m.role}\n{m.flat_text()}<|im_end|>\n")
        out.append("<|im_start|>assistant\n")
        return "".join(out)


def parse_messages(raw: List[Dict[str, Any]]) -> List[Message]:
    return [Message.from_json(j) for j in raw]
