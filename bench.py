"""Benchmark driver: continuous-batching decode throughput on the flagship
model (single chip). Prints ONE JSON line.

`vs_baseline` is measured against the only quantitative anchor the reference
publishes (BASELINE.md): its SLO defaults — 50 ms TPOT ⇒ 20 output tok/s per
running request, times the decode batch. >1.0 means every slot in the batch
beats the reference's per-request latency SLO.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

import numpy as np

# How long to give the TPU tunnel to come up before falling back to CPU.
# Round 1's bench crashed (rc=1) because the axon sitecustomize forces the
# TPU platform at interpreter start and backend init raised/hung when the
# tunnel was down; the bench must always print a number.
_TPU_PROBE_TIMEOUT_S = float(os.environ.get("XLLM_BENCH_TPU_PROBE_TIMEOUT", 300))


def _probe_backend() -> str:
    """Return 'tpu' iff a TPU backend initializes in a SUBPROCESS within the
    timeout (a hung tunnel must not hang the bench itself), else 'cpu'."""
    if os.environ.get("XLLM_BENCH_FORCE_CPU"):
        return "cpu"
    try:
        r = subprocess.run(
            [sys.executable, "-c",
             "import jax; jax.devices(); print(jax.default_backend())"],
            capture_output=True, text=True, timeout=_TPU_PROBE_TIMEOUT_S,
        )
    except subprocess.TimeoutExpired:
        return "cpu"
    if r.returncode == 0 and r.stdout.strip().splitlines()[-1:] == ["tpu"]:
        return "tpu"
    return "cpu"


# Per-attempt wall-clock cap: a config whose kernel COMPILES but then
# wedges the device/tunnel (observed failure mode of the axon tunnel:
# a client blocks in recv forever) must not take the whole bench down —
# exceptions already fall through; hangs need a subprocess boundary.
# Default keeps 4 attempts + probe under the supervisor's 3600 s outer
# budget (scripts/tpu_supervisor.py BENCH_TIMEOUT).
_ATTEMPT_TIMEOUT_S = float(os.environ.get("XLLM_BENCH_ATTEMPT_TIMEOUT", 780))


def _run_attempt_subprocess(child_cfg: dict) -> "tuple[int, str, str]":
    """One attempt in its own PROCESS GROUP: a wedged child (or any
    helper process it forked holding the pipe FDs) is killed as a group,
    so the parent's pipe reads always terminate. Returns (rc, out, err);
    rc < 0 means timeout-killed."""
    import signal

    proc = subprocess.Popen(
        [sys.executable, os.path.abspath(__file__),
         "--attempt-json", json.dumps(child_cfg)],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        start_new_session=True,
    )
    try:
        out, err = proc.communicate(timeout=_ATTEMPT_TIMEOUT_S)
        return proc.returncode, out, err
    except subprocess.TimeoutExpired:
        try:
            os.killpg(proc.pid, signal.SIGKILL)
        except (ProcessLookupError, PermissionError):
            proc.kill()
        out, err = proc.communicate()
        return -1, out or "", err or ""


# Best recorded clean-load CPU decode figure (BENCH_r04: 4,262.9 tok/s at
# loadavg 0.2). The guard fails the bench loudly — annotated JSON + exit 3
# AFTER the number prints, so the record survives — when a clean-load CPU
# run lands >5% below it, instead of letting a regression ride silently
# into the record as r5's 4,263 -> 3,902 did (VERDICT r5 #2). Raise this
# anchor whenever a faster clean-load CPU figure is recorded.
_BEST_CPU_DECODE_TOK_S = float(os.environ.get("XLLM_BENCH_CPU_BEST", 4262.9))
# r3 precedent: host load masquerades as regression. Above this 1-min
# loadavg (before or after the timed runs) the guard abstains.
_GUARD_LOADAVG_CEILING = float(os.environ.get("XLLM_BENCH_GUARD_LOAD", 1.0))
# Host-class gate: a 2-CPU dev container lands ~1,400 tok/s at loadavg 0.0
# on the SAME tree that does 4,263 on the r4 driver host (r3's 1,600 was
# the same effect) — an absolute anchor only means anything on hosts of
# the class that recorded it, so the guard abstains below this CPU count.
_GUARD_MIN_CPUS = int(os.environ.get("XLLM_BENCH_GUARD_MIN_CPUS", 4))


# Overlapped-engine A/B guard: the overlapped (default) engine must not
# land below this fraction of the sync escape hatch's throughput in the
# same run — the pipeline paying MORE than it hides is a regression.
_OVERLAP_MIN_RATIO = float(os.environ.get("XLLM_BENCH_OVERLAP_MIN_RATIO", 0.92))

# Mixed-vs-split attention A/B guard (--attention-mode both): the fused
# mixed-step engine (one ragged dispatch per iteration, docs/KERNELS.md)
# must hold at least this fraction of split-step throughput — fusing the
# hot loop can never be allowed to regress silently (ISSUE 9).
_RAGGED_MIN_RATIO = float(os.environ.get("XLLM_BENCH_RAGGED_MIN_RATIO", 0.95))

# Combined-path A/B guard (--spec-mode both, ISSUE 13): with speculative
# decoding ON, the composed engine (overlap pipeline + mixed verify
# batch) must hold at least this fraction of the sync+split verify
# engine's throughput — composing the fast paths must never pay more
# than it hides (the real win is on TPU; CPU arms the floor).
_SPEC_MIN_RATIO = float(os.environ.get("XLLM_BENCH_SPEC_MIN_RATIO", 0.95))

# Latency-hiding collectives A/B guard (--overlap both, ISSUE 18): with
# the ring collective-matmul schedule ON (XLLM_OVERLAP_COLLECTIVES=1,
# docs/SHARDING.md "Hiding the mesh"), the sharded engine must hold at
# least this fraction of the plain-psum row's throughput — decomposing
# the combines buys overlap headroom and must never pay more than it
# hides. Distinct env from XLLM_BENCH_OVERLAP_MIN_RATIO, which floors
# the engine PIPELINE overlap (sync-vs-overlap stepping), not the
# collective schedule.
_OVERLAP_COLL_MIN_RATIO = float(
    os.environ.get("XLLM_BENCH_OVERLAP_COLL_MIN_RATIO", 0.97)
)

# Warm-start host-gap ceiling (ms) for the default engine row: after the
# compile-cache prewarm the first post-idle dispatch must NOT pay a
# fresh XLA compile (PR 11 measured that ambush at 2.7-4 s; steady-state
# host gap on the record is <1 ms) — a mean above this ceiling on a
# clean-load host means programs are compiling inside the serving loop.
_HOST_GAP_MAX_MS = float(os.environ.get("XLLM_BENCH_HOST_GAP_MAX_MS", 25.0))


def _cpu_regression_guard(line: str) -> "tuple[str, int]":
    """Apply the >5% clean-load CPU decode regression guard — and the
    overlap-vs-sync engine A/B guard — to the result line. Returns
    (annotated line, exit code); nonzero means regression."""
    if os.environ.get("XLLM_BENCH_NO_REGRESSION_GUARD"):
        return line, 0
    try:
        res = json.loads(line)
    except ValueError:
        return line, 0
    if res.get("backend") != "cpu" or _BEST_CPU_DECODE_TOK_S <= 0:
        return line, 0
    load = max(
        float(res.get("loadavg_1m_start") or 0.0),
        float(res.get("loadavg_1m") or 0.0),
    )
    value = float(res.get("value") or 0.0)
    ncpu = os.cpu_count() or 1
    if ncpu < _GUARD_MIN_CPUS:
        res["cpu_regression_guard"] = (
            f"abstained: {ncpu}-CPU host below the anchor's class "
            f"(set XLLM_BENCH_CPU_BEST for a local anchor)"
        )
        return json.dumps(res), 0
    if load > _GUARD_LOADAVG_CEILING:
        res["cpu_regression_guard"] = f"abstained: loadavg {load:.1f}"
        return json.dumps(res), 0
    rc = 0
    if value >= 0.95 * _BEST_CPU_DECODE_TOK_S:
        res["cpu_regression_guard"] = "ok"
    else:
        res["cpu_regression_guard"] = (
            f"FAIL: {value:.1f} tok/s is "
            f"{100.0 * (1.0 - value / _BEST_CPU_DECODE_TOK_S):.1f}% below "
            f"the best recorded clean-load CPU figure "
            f"{_BEST_CPU_DECODE_TOK_S:.1f}"
        )
        rc = 3
    # Engine-level A/B (runs against the overlapped DEFAULT mode): present
    # only when the run measured both modes.
    eb = res.get("engine_bench") or {}
    if isinstance(eb, dict) and "sync" in eb and "overlap" in eb:
        try:
            s = float(eb["sync"]["tok_s"])
            o = float(eb["overlap"]["tok_s"])
        except (KeyError, TypeError, ValueError):
            s = o = 0.0
        if s <= 0:
            pass
        elif o >= _OVERLAP_MIN_RATIO * s:
            res["engine_overlap_guard"] = "ok"
        else:
            res["engine_overlap_guard"] = (
                f"FAIL: overlapped engine {o:.1f} tok/s is below "
                f"{100 * _OVERLAP_MIN_RATIO:.0f}% of sync mode {s:.1f}"
            )
            rc = rc or 3
    # Attention-mode A/B (--attention-mode both): the mixed (ragged) step
    # builder vs the split-step escape hatch.
    ab = res.get("attention_bench") or {}
    if isinstance(ab, dict) and "split" in ab and "ragged" in ab:
        try:
            s = float(ab["split"]["tok_s"])
            g = float(ab["ragged"]["tok_s"])
        except (KeyError, TypeError, ValueError):
            s = g = 0.0
        # The rows must have RUN the builders they are labeled as — an
        # XLLM_MIXED_STEP env override wins over the per-run config, and
        # a split-vs-split comparison stamping "ok" would defeat the
        # guard's whole purpose.
        builders = (
            ab["split"].get("step_builder"),
            ab["ragged"].get("step_builder"),
        )
        if builders != ("split", "ragged"):
            res["engine_ragged_guard"] = (
                f"abstained: step_builder {builders[0]}/{builders[1]} — "
                f"an env override pinned the builder (XLLM_MIXED_STEP?)"
            )
        elif s <= 0:
            pass
        elif g >= _RAGGED_MIN_RATIO * s:
            res["engine_ragged_guard"] = "ok"
        else:
            res["engine_ragged_guard"] = (
                f"FAIL: mixed (ragged) engine {g:.1f} tok/s is below "
                f"{100 * _RAGGED_MIN_RATIO:.0f}% of split mode {s:.1f}"
            )
            rc = rc or 3
    # Combined-path A/B (--spec-mode both): speculative decode through
    # the composed overlap+mixed pipeline vs the sync+split verify
    # engine (ISSUE 13).
    sb = res.get("spec_bench") or {}
    if isinstance(sb, dict) and "composed" in sb and "sync_split" in sb:
        try:
            s = float(sb["sync_split"]["tok_s"])
            c = float(sb["composed"]["tok_s"])
        except (KeyError, TypeError, ValueError):
            s = c = 0.0
        # The rows must have RUN the builders they are labeled as: the
        # XLLM_SPEC_PIPELINE / XLLM_SYNC_ENGINE / XLLM_MIXED_STEP env
        # hatches win over the per-run config, and a sync-vs-sync
        # comparison stamping "ok" would defeat the guard — abstain
        # loudly on a builder mismatch, like engine_ragged_guard.
        builders = (
            sb["composed"].get("step_builder"),
            sb["sync_split"].get("step_builder"),
        )
        if builders != ("spec-overlap+mixed", "spec-sync+split"):
            # "spec-overlap+split" for the composed row is also a
            # legitimate label: the model family has no
            # mixed_verify_step (MLA), so verify rows pipelined without
            # prefill fusion — name both causes instead of sending the
            # operator hunting for hatches that were never set.
            cause = (
                "the family lacks mixed_verify_step (no spec+mixed "
                "fusion)"
                if builders[0] == "spec-overlap+split"
                and builders[1] == "spec-sync+split"
                else "an env override pinned the builder "
                "(XLLM_SPEC_PIPELINE/XLLM_SYNC_ENGINE/XLLM_MIXED_STEP?)"
            )
            res["engine_spec_guard"] = (
                f"abstained: step_builder {builders[0]}/{builders[1]} — "
                f"{cause}"
            )
        elif s <= 0:
            pass
        elif c >= _SPEC_MIN_RATIO * s:
            res["engine_spec_guard"] = "ok"
        else:
            res["engine_spec_guard"] = (
                f"FAIL: composed spec engine {c:.1f} tok/s is below "
                f"{100 * _SPEC_MIN_RATIO:.0f}% of sync+split {s:.1f}"
            )
            rc = rc or 3
    return json.dumps(res), rc


# Grouped-MoE A/B guard (--moe both, ISSUE 15): the grouped ragged
# expert dispatch must hold at least this fraction of the dense
# all-experts einsum's decode throughput — the dispatch that exists to
# make compute track ACTIVE params can never be allowed to regress
# silently. Armed only when the grouped row actually RESOLVED to the
# Pallas "grouped" dispatch (docs/MOE.md) — on CPU the row runs the
# blockwise oracle ("grouped-ref"), whose job is parity, not speed.
_MOE_MIN_RATIO = float(os.environ.get("XLLM_BENCH_MOE_MIN_RATIO", 0.95))


def _moe_guard(line: str) -> "tuple[str, int]":
    """Exit-3 guard for the --moe A/B rows; abstains LOUDLY on a
    dispatch mismatch (the engine_spec_guard builder-mismatch
    pattern)."""
    try:
        res = json.loads(line)
    except ValueError:
        return line, 0
    mb = res.get("moe_bench") or {}
    if not isinstance(mb, dict) or "grouped" not in mb or "dense" not in mb:
        return line, 0
    try:
        d = float(mb["dense"]["tok_s"])
        g = float(mb["grouped"]["tok_s"])
    except (KeyError, TypeError, ValueError):
        d = g = 0.0
    disp = (
        mb["grouped"].get("moe_dispatch"),
        mb["dense"].get("moe_dispatch"),
    )
    if disp[0] != "grouped" or str(disp[1] or "").startswith("grouped"):
        res["engine_moe_guard"] = (
            f"abstained: moe_dispatch {disp[0]}/{disp[1]} — the grouped "
            f"row must run the Pallas grouped dispatch and the dense row "
            f"the all-experts einsum (CPU resolves grouped-ref: parity "
            f"is tier-1's tests/test_moe_engine.py; the floor arms on "
            f"TPU)"
        )
        return json.dumps(res), 0
    if mb["grouped"].get("moe_interpret") or mb["dense"].get(
        "moe_interpret"
    ):
        # XLLM_MOE_INTERPRET rows time the Pallas INTERPRETER against
        # compiled XLA — a guaranteed sub-floor ratio that says nothing
        # about the chip; a CI host exporting the hook must not fail
        # the bench.
        res["engine_moe_guard"] = (
            "abstained: XLLM_MOE_INTERPRET is set — interpret-mode "
            "rows measure the interpreter, not the dispatch"
        )
        return json.dumps(res), 0
    if d <= 0 or g <= 0:
        # Still loud: a harness refactor that loses tok_s must not make
        # the guard silently vanish.
        res["engine_moe_guard"] = (
            f"abstained: unparseable tok_s (grouped={g}, dense={d})"
        )
        return json.dumps(res), 0
    if g >= _MOE_MIN_RATIO * d:
        res["engine_moe_guard"] = "ok"
        return json.dumps(res), 0
    res["engine_moe_guard"] = (
        f"FAIL: grouped MoE dispatch {g:.1f} tok/s is below "
        f"{100 * _MOE_MIN_RATIO:.0f}% of the dense all-experts path "
        f"{d:.1f}"
    )
    return json.dumps(res), 3


def _overlap_guard(line: str) -> "tuple[str, int]":
    """Exit-3 guards for the --overlap A/B rows and the warm-start host
    gap (ISSUE 18). `engine_overlap_collectives_guard` floors the ring
    collective-matmul row against plain psum and abstains LOUDLY when
    the labeled rows did not actually route the schedule (the
    engine_moe_guard dispatch-mismatch pattern); `engine_host_gap_guard`
    ceilings the default engine row's mean host gap so an
    in-serving-loop recompile can never ride into the record as a tok/s
    blip."""
    if os.environ.get("XLLM_BENCH_NO_REGRESSION_GUARD"):
        return line, 0
    try:
        res = json.loads(line)
    except ValueError:
        return line, 0
    rc = 0
    load = max(
        float(res.get("loadavg_1m_start") or 0.0),
        float(res.get("loadavg_1m") or 0.0),
    )
    ob = res.get("overlap_bench") or {}
    if isinstance(ob, dict) and "on" in ob and "off" in ob:
        routed = (
            ob["on"].get("overlap_collectives"),
            ob["off"].get("overlap_collectives"),
        )
        try:
            on = float(ob["on"]["tok_s"])
            off = float(ob["off"]["tok_s"])
        except (KeyError, TypeError, ValueError):
            on = off = 0.0
        if routed != (True, False):
            # The documented abstention: on a single-device mesh
            # (tp=1, ep=1) the ring schedule is ineligible by design —
            # both rows ran the original einsum and a floor over them
            # would stamp "ok" on nothing. Also covers an env override
            # pinning the hatch under both labels.
            cause = (
                "the ring schedule never engaged (single-device mesh — "
                "run --mesh 1,N,1; parity/eligibility is tier-1's "
                "tests/test_overlap_collectives.py)"
                if routed == (False, False)
                else "an env override pinned the hatch "
                "(XLLM_OVERLAP_COLLECTIVES?)"
            )
            res["engine_overlap_collectives_guard"] = (
                f"abstained: overlap_collectives {routed[0]}/{routed[1]}"
                f" — {cause}"
            )
        elif res.get("backend") != "tpu":
            # The mesh-guard precedent: a CPU virtual mesh proves
            # routing (the rows above carry overlap_collectives
            # True/False) but not performance — every ppermute hop is a
            # same-host memcpy with no ICI to hide it behind, so the
            # ring reads as pure overhead and the floor would flake.
            res["engine_overlap_collectives_guard"] = (
                "abstained: virtual CPU mesh — ppermute hops have no "
                "ICI to hide behind off-TPU; the floor arms on TPU "
                "(bit-parity is tier-1's tests/test_overlap_collectives"
                ".py)"
            )
        elif load > _GUARD_LOADAVG_CEILING:
            res["engine_overlap_collectives_guard"] = (
                f"abstained: loadavg {load:.1f}"
            )
        elif on <= 0 or off <= 0:
            res["engine_overlap_collectives_guard"] = (
                f"abstained: unparseable tok_s (on={on}, off={off})"
            )
        elif on >= _OVERLAP_COLL_MIN_RATIO * off:
            res["engine_overlap_collectives_guard"] = "ok"
        else:
            res["engine_overlap_collectives_guard"] = (
                f"FAIL: collective-matmul engine {on:.1f} tok/s is "
                f"below {100 * _OVERLAP_COLL_MIN_RATIO:.0f}% of the "
                f"psum row {off:.1f}"
            )
            rc = 3
    # Warm-start host-gap ceiling on the default (overlapped) engine
    # row: the timed repeats run after the warm passes, so a mean above
    # the ceiling means a program compiled INSIDE the serving loop —
    # exactly the post-idle ambush the compile-cache prewarm exists to
    # kill. Timing-based absolute ceiling, so it inherits the CPU
    # guard's host-class and load abstentions.
    eb = res.get("engine_bench") or {}
    row = eb.get("overlap") if isinstance(eb, dict) else None
    if isinstance(row, dict) and row.get("host_gap_ms_mean") is not None:
        gap = float(row["host_gap_ms_mean"])
        ncpu = os.cpu_count() or 1
        if ncpu < _GUARD_MIN_CPUS:
            res["engine_host_gap_guard"] = (
                f"abstained: {ncpu}-CPU host below the ceiling's class"
            )
        elif load > _GUARD_LOADAVG_CEILING:
            res["engine_host_gap_guard"] = f"abstained: loadavg {load:.1f}"
        elif gap <= _HOST_GAP_MAX_MS:
            res["engine_host_gap_guard"] = "ok"
        else:
            res["engine_host_gap_guard"] = (
                f"FAIL: warm-start host gap {gap:.3f} ms exceeds the "
                f"{_HOST_GAP_MAX_MS:.0f} ms ceiling — a program is "
                f"compiling inside the serving loop (compile-cache "
                f"prewarm missed a variant? see compile_cache_bench)"
            )
            rc = rc or 3
    return json.dumps(res), rc


# Sharded-decode roofline guard (--mesh, ROADMAP item 3): on TPU a
# tp-sharded decode must land at least this fraction of its analytic
# per-shard roofline expectation — a GSPMD-replicated kernel or a silent
# gather fallback is ~tp× off, which this catches loudly (exit 3)
# instead of letting a degraded multi-chip round into the record.
_MESH_MIN_ROOFLINE_RATIO = float(
    os.environ.get("XLLM_BENCH_MESH_MIN_RATIO", 0.5)
)


def _mesh_guard(line: str) -> "tuple[str, int]":
    """Exit-3 guard for --mesh rows. Abstains LOUDLY off-TPU (the same
    pattern as engine_spec_guard): a CPU virtual mesh proves parity in
    tier-1, not performance — the floor arms only where the roofline
    means something."""
    try:
        res = json.loads(line)
    except ValueError:
        return line, 0
    m = res.get("mesh") or {}
    if not isinstance(m, dict) or m.get("dp", 1) * m.get("tp", 1) * m.get(
        "ep", 1
    ) <= 1:
        return line, 0
    if res.get("backend") != "tpu":
        res["engine_mesh_guard"] = (
            "abstained: virtual CPU mesh — shard parity is tier-1's "
            "differential suite (tests/test_sharded_engine.py); the "
            "per-shard roofline floor arms on TPU"
        )
        return json.dumps(res), 0
    try:
        value = float(res.get("value") or 0.0)
        expect = float(res["decode_roofline"]["expected_tok_s"])
    except (KeyError, TypeError, ValueError):
        return line, 0
    if expect <= 0:
        return line, 0
    if value >= _MESH_MIN_ROOFLINE_RATIO * expect:
        res["engine_mesh_guard"] = "ok"
        return json.dumps(res), 0
    res["engine_mesh_guard"] = (
        f"FAIL: sharded decode {value:.1f} tok/s is below "
        f"{100 * _MESH_MIN_ROOFLINE_RATIO:.0f}% of the per-shard "
        f"roofline expectation {expect:.1f} — GSPMD-replicated kernel "
        f"or gather fallback? (see kernel_shards / attention_kernel)"
    )
    return json.dumps(res), 3


def main() -> None:
    if "--attempt-json" in sys.argv:
        # child mode: run exactly one config in THIS process
        cfg = json.loads(sys.argv[sys.argv.index("--attempt-json") + 1])
        on_tpu = cfg.pop("_on_tpu")
        if not on_tpu:
            from __graft_entry__ import _force_cpu_platform

            # CPU mesh runs need that many VIRTUAL host devices — the
            # same --xla_force_host_platform_device_count trick the
            # tier-1 differential suite runs on (docs/SHARDING.md).
            dp, tp, ep = cfg.get("mesh", (1, 1, 1))
            _force_cpu_platform(max(1, dp * tp * ep))
        _run(on_tpu, **cfg)
        return

    # --mesh dp,tp,ep: bench a SHARDED engine (ROADMAP item 3). On TPU
    # this is the real multi-chip GSPMD tier (tp-sharded 70B-class
    # decode, per-shard Pallas dispatch); on CPU it runs the same code
    # on the virtual host mesh so MULTICHIP/BENCH rounds get comparable
    # shard-aware rows before a chip window opens. Default 1,1,1.
    mesh = (1, 1, 1)
    if "--mesh" in sys.argv:
        raw = sys.argv[sys.argv.index("--mesh") + 1]
        try:
            parts = [int(x) for x in raw.split(",")]
        except ValueError:
            parts = []
        if len(parts) != 3 or any(p < 1 for p in parts):
            raise SystemExit(f"--mesh must be dp,tp,ep integers, got {raw!r}")
        mesh = tuple(parts)

    # --engine-mode {sync,overlap,both}: which InferenceEngine stepping
    # mode(s) the engine-level A/B section measures (docs/ENGINE_PIPELINE.md).
    # Default "both" reports the A/B pair and arms the overlap guard.
    engine_mode = "both"
    if "--engine-mode" in sys.argv:
        engine_mode = sys.argv[sys.argv.index("--engine-mode") + 1]
        if engine_mode not in ("sync", "overlap", "both"):
            raise SystemExit(
                f"--engine-mode must be sync|overlap|both, got {engine_mode!r}"
            )

    # --attention-mode {split,ragged,both}: mixed (ragged) stepping vs the
    # split-step escape hatch (docs/KERNELS.md), mirroring --engine-mode.
    # Default "both" reports the A/B pair and arms the ragged guard.
    attention_mode = "both"
    if "--attention-mode" in sys.argv:
        attention_mode = sys.argv[sys.argv.index("--attention-mode") + 1]
        if attention_mode not in ("split", "ragged", "both"):
            raise SystemExit(
                f"--attention-mode must be split|ragged|both, "
                f"got {attention_mode!r}"
            )

    # --spec-mode {composed,sync,both}: the combined-path A/B (ISSUE 13)
    # — speculative decoding through the composed overlap+mixed pipeline
    # vs the sync+split verify engine. Default "both" reports the pair
    # and arms the engine_spec_guard.
    spec_mode = "both"
    if "--spec-mode" in sys.argv:
        spec_mode = sys.argv[sys.argv.index("--spec-mode") + 1]
        if spec_mode not in ("composed", "sync", "both"):
            raise SystemExit(
                f"--spec-mode must be composed|sync|both, got {spec_mode!r}"
            )

    # --moe {grouped,dense,both}: the MoE dispatch A/B (ISSUE 15) — the
    # grouped ragged expert dispatch vs the dense all-experts einsum on
    # the MoE tiny model at matched active params. Default "both"
    # reports the pair and arms the engine_moe_guard.
    moe_mode = "both"
    if "--moe" in sys.argv:
        idx = sys.argv.index("--moe") + 1
        nxt = sys.argv[idx] if idx < len(sys.argv) else ""
        if nxt in ("grouped", "dense", "both"):
            moe_mode = nxt
        elif nxt and not nxt.startswith("-"):
            raise SystemExit(
                f"--moe takes grouped|dense|both, got {nxt!r}"
            )
        # bare `--moe` (or followed by another flag) = "both"

    # --overlap {on,off,both}: the latency-hiding collectives A/B
    # (ISSUE 18) — the ring collective-matmul schedule
    # (XLLM_OVERLAP_COLLECTIVES=1, docs/SHARDING.md) vs the plain
    # psum/einsum combines, on the tp-sharded engine. Default "both"
    # reports the pair and arms engine_overlap_collectives_guard.
    overlap_mode = "both"
    if "--overlap" in sys.argv:
        idx = sys.argv.index("--overlap") + 1
        nxt = sys.argv[idx] if idx < len(sys.argv) else ""
        if nxt in ("on", "off", "both"):
            overlap_mode = nxt
        elif nxt and not nxt.startswith("-"):
            raise SystemExit(f"--overlap takes on|off|both, got {nxt!r}")
        # bare `--overlap` (or followed by another flag) = "both"

    backend = _probe_backend()
    on_tpu = backend == "tpu"
    # Fastest config first; fall back if a path that never ran on real
    # hardware this round fails to compile OR hangs — the bench must
    # ALWAYS print a number (round-1 lesson; hang isolation round 3).
    attempts = (
        [
            # Fastest first: int8 weights (halves weight HBM traffic —
            # decode's dominant stream) + int8 KV + both Pallas kernels.
            {"kv_cache_dtype": "int8", "weight_dtype": "int8"},
            {"kv_cache_dtype": "int8"},
            {"kv_cache_dtype": "auto"},
            {"kv_cache_dtype": "auto", "use_kernel": False},
        ]
        if on_tpu
        else [{"kv_cache_dtype": "auto"}]
    )
    last_err = None
    for attempt in attempts:
        rc, out, err = _run_attempt_subprocess(
            dict(attempt, engine_mode=engine_mode,
                 attention_mode=attention_mode, spec_mode=spec_mode,
                 moe_mode=moe_mode, overlap_mode=overlap_mode,
                 mesh=list(mesh), _on_tpu=on_tpu)
        )
        line = ""
        for ln in out.splitlines():
            if ln.startswith("{"):
                line = ln
        if rc == 0 and line:
            line, guard_rc = _cpu_regression_guard(line)
            line, mesh_rc = _mesh_guard(line)
            line, moe_rc = _moe_guard(line)
            line, ovl_rc = _overlap_guard(line)
            guard_rc = guard_rc or mesh_rc or moe_rc or ovl_rc
            print(line)
            if guard_rc:
                print(
                    "# CPU decode regression guard tripped — see the "
                    "cpu_regression_guard field", file=sys.stderr,
                )
                sys.exit(guard_rc)
            return
        sys.stderr.write(err[-4000:])
        last_err = (
            f"attempt {attempt} timed out after {_ATTEMPT_TIMEOUT_S:.0f}s"
            if rc < 0
            else f"attempt {attempt} rc={rc}"
        )
        print(f"# {last_err}", file=sys.stderr)
    raise SystemExit(f"all bench configs failed: {last_err}")


def _engine_bench(sync: bool, mixed: bool = True, spec: int = 0,
                  model: str = "llama3-tiny",
                  moe: "str | None" = None,
                  overlap: "str | None" = None,
                  tp: int = 1) -> dict:
    """Full-InferenceEngine decode throughput (llama3-tiny, R=8) in one
    stepping mode: R seeded requests driven to completion through the real
    admission/decode/emit path. Reports tokens/s plus the pipeline
    instruments — mean host_gap_ms (host bookkeeping between steps), the
    fraction of decode steps dispatched with another step in flight, the
    fraction of dispatches that fused prefill rows with the decode batch
    (`mixed` stepping, docs/KERNELS.md), and the RESOLVED attention
    kernel the engine's dispatches actually route to. `spec` > 0 runs
    the same harness under speculative decoding (the ISSUE 13 combined
    path: sync/mixed then select composed vs sync+split verify).
    `moe` pins the MoE dispatch for the --moe A/B (ISSUE 15):
    "grouped" sets XLLM_MOE_KERNEL=1 around the run, "dense" =0 — the
    row reports the dispatch the executor actually RESOLVED (the guard
    abstains when the grouped row ran the oracle, e.g. on CPU).
    `overlap` pins the collective-matmul schedule the same way for the
    --overlap A/B (ISSUE 18): "on" sets XLLM_OVERLAP_COLLECTIVES=1,
    "off" =0 — the row reports `overlap_collectives`, whether the ring
    schedule was actually ELIGIBLE (tp>1/ep>1), which the guard keys
    on. `tp` runs the engine tp-sharded (needs that many devices)."""
    import numpy as np

    from xllm_service_tpu.common.config import EngineConfig
    from xllm_service_tpu.ops.sampling import SamplingParams
    from xllm_service_tpu.runtime.engine import EngineRequest, InferenceEngine
    from xllm_service_tpu.runtime.executor import ModelExecutor

    if moe is not None:
        # Pin the dispatch around the WHOLE run (env is read at trace
        # time; later bucket shapes retrace mid-run) and restore — a
        # later A/B row must not inherit the override.
        prev_moe_env = os.environ.get("XLLM_MOE_KERNEL")
        os.environ["XLLM_MOE_KERNEL"] = "1" if moe == "grouped" else "0"
        try:
            row = _engine_bench(sync, mixed=mixed, spec=spec, model=model)
            row["moe_mode"] = moe
            return row
        finally:
            if prev_moe_env is None:
                os.environ.pop("XLLM_MOE_KERNEL", None)
            else:
                os.environ["XLLM_MOE_KERNEL"] = prev_moe_env

    if overlap is not None:
        # Same pin-around-the-WHOLE-run pattern as `moe`: the hatch is
        # read at trace time and later bucket shapes retrace mid-run,
        # so a leaky override would split one row across schedules.
        prev_ovl_env = os.environ.get("XLLM_OVERLAP_COLLECTIVES")
        os.environ["XLLM_OVERLAP_COLLECTIVES"] = (
            "1" if overlap == "on" else "0"
        )
        try:
            row = _engine_bench(
                sync, mixed=mixed, spec=spec, model=model, tp=tp
            )
            row["overlap_mode"] = overlap
            return row
        finally:
            if prev_ovl_env is None:
                os.environ.pop("XLLM_OVERLAP_COLLECTIVES", None)
            else:
                os.environ["XLLM_OVERLAP_COLLECTIVES"] = prev_ovl_env

    R, prompt_len, new_tokens = 8, 32, 48
    cfg = EngineConfig(
        model=model,
        dtype="float32",
        block_size=16,
        num_blocks=64,
        max_running_requests=R,
        max_seq_len=128 if tp > 1 else 256,
        prefill_buckets=[32, 64, 128] if tp > 1 else [32, 64, 128, 256],
        tp_size=tp,
        sync_engine=sync,
        enable_mixed_step=mixed,
        speculative_tokens=spec,
        # Composed path under test iff the engine is NOT pinned sync —
        # sync=True + spec gives exactly the pre-ISSUE-13 verify loop.
        enable_spec_pipeline=not sync,
    )
    eng = InferenceEngine(cfg, executor=ModelExecutor(cfg))
    rng = np.random.default_rng(0)
    prompts = [
        rng.integers(0, eng.executor.cfg.vocab_size, (prompt_len,)).tolist()
        for _ in range(R)
    ]

    def run_once(tag):
        emitted = [0]

        def cb(out):
            for so in out.outputs:
                emitted[0] += len(so.token_ids)
            return True

        t0 = time.perf_counter()
        for i, p in enumerate(prompts):
            eng.add_request(EngineRequest(
                request_id=f"{tag}-{i}",
                prompt_token_ids=list(p),
                sampling=SamplingParams(
                    temperature=0.7, seed=i + 1, max_new_tokens=new_tokens,
                ),
                callback=cb,
            ))
        while eng.has_work():
            eng.step()
        return emitted[0], time.perf_counter() - t0

    run_once("warm")  # compile every shape outside the timing
    if spec and not sync:
        # Second warm pass for the pipelined verify: a first post-idle
        # dispatch sees device-provenance prev/cache arrays that the
        # cold boot's numpy-fed shapes didn't cover — one more full
        # cycle compiles those variants outside the timed window too.
        run_once("warm2")
    repeats = int(os.environ.get("XLLM_BENCH_ENGINE_REPEATS", 3))
    gap0, gsteps0 = eng.host_gap_ms_sum, eng.host_gap_steps
    ov0, disp0 = eng.overlap_steps, eng.decode_dispatches
    disc0, mix0 = eng.late_stop_discards, eng.mixed_steps
    emit0, sstep0 = eng.spec_tokens_emitted, eng.spec_slot_steps
    pipe0, spec0 = eng.spec_pipeline_steps, eng.spec_steps
    coll0 = eng.collective_overlap_steps
    dts, toks = [], 0
    for r in range(repeats):
        n, dt = run_once(f"t{r}")
        toks = n
        dts.append(dt)
    dt = float(np.median(dts))
    gap_steps = max(eng.host_gap_steps - gsteps0, 1)
    dispatches = max(eng.decode_dispatches - disp0, 1)
    # The builder the engine actually RAN, not the config knob: sync mode
    # forces the split path even with mixed enabled, and the env hatches
    # (XLLM_SYNC_ENGINE/XLLM_SPEC_PIPELINE/XLLM_MIXED_STEP) win over the
    # per-run config — the guards abstain on a label mismatch.
    pipelined = not eng._force_sync
    mixed_ran = eng.mixed_step_enabled and pipelined
    if spec:
        spec_fuse = mixed_ran and getattr(
            eng.executor, "supports_spec_mixed", False
        )
        builder = (
            "spec-overlap+mixed" if pipelined and spec_fuse
            else "spec-overlap+split" if pipelined
            else "spec-sync+split"
        )
    else:
        builder = "ragged" if mixed_ran else "split"
    row = {
        "mode": "sync" if sync else "overlap",
        "step_builder": builder,
        # The dispatch decision the engine RESOLVED for the step builder
        # it actually ran — the fused step's kernel (ragged vs the
        # mixed[<decode>+<prefill>] reference pair), or the split
        # builder's separate pair — not the raw env var (ISSUE 9
        # satellite).
        "kernel": (
            eng._kernel_names["mixed"] if mixed_ran
            else f"split[{eng._kernel_names['decode']}+"
            f"{eng._kernel_names['prefill']}]"
        ),
        "tok_s": round(toks / dt, 1),
        "host_gap_ms_mean": round(
            (eng.host_gap_ms_sum - gap0) / gap_steps, 3
        ),
        "overlap_step_frac": round(
            (eng.overlap_steps - ov0) / dispatches, 3
        ),
        "mixed_step_frac": round(
            (eng.mixed_steps - mix0) / dispatches, 3
        ),
        "late_stop_discards": eng.late_stop_discards - disc0,
        "requests": R,
        "new_tokens": new_tokens,
        # Whether the ring collective-matmul schedule was ELIGIBLE for
        # this geometry (hatch on AND tp>1/ep>1) plus the steps that
        # dispatched through it — engine_overlap_collectives_guard keys
        # on the flag, never the raw env var (ISSUE 18).
        "overlap_collectives": bool(
            getattr(eng.executor, "overlap_collectives_active", False)
        ),
        "collective_overlap_steps": eng.collective_overlap_steps - coll0,
    }
    if getattr(eng.executor.cfg, "is_moe", False):
        # Resolved MoE dispatch + the expert-load signal (ISSUE 15):
        # the guard keys on moe_dispatch, not the env var — and on the
        # interpret hook, whose rows measure the interpreter.
        rep = eng.executor.kernel_report()
        row["moe_dispatch"] = rep.get("moe")
        row["moe_shards"] = rep.get("moe_shards", 1)
        row["moe_interpret"] = (
            os.environ.get("XLLM_MOE_INTERPRET") == "1"
        )
        stats = eng.executor.moe_stats(drain=True)
        row["moe_hot_expert_frac"] = round(stats["hot_expert_frac"], 3)
        row["moe_dropped_assignments"] = stats["dropped"]
    if spec:
        # Realized speculative speedup + how the verify steps routed —
        # deltas over the timed repeats only, like the other counters
        # (the warm passes must not fold into the A/B rows).
        row["spec_tokens"] = spec
        row["accepted_len_mean"] = round(
            (eng.spec_tokens_emitted - emit0)
            / max(eng.spec_slot_steps - sstep0, 1), 3
        )
        row["spec_pipeline_step_frac"] = round(
            (eng.spec_pipeline_steps - pipe0)
            / max(eng.spec_steps - spec0, 1), 3
        )
    return row


def _compile_cache_bench() -> dict:
    """Cold-vs-warm persistent compile cache A/B (ISSUE 18 tentpole b):
    two fresh executors prewarmed against ONE keyed on-disk cache dir —
    the cold pass pays every XLA compile, the warm pass (new jit
    wrappers, so jaxpr lowering still runs) reloads the executables
    from disk, which is exactly what a restarted instance with the same
    geometry sees. Minimal geometry (one prefill bucket, mixed step
    off) keeps the section to seconds; the absolute delta scales with
    the real bucket-program family."""
    import shutil
    import tempfile

    from xllm_service_tpu.common.config import EngineConfig
    from xllm_service_tpu.runtime import compile_cache as cc
    from xllm_service_tpu.runtime.executor import ModelExecutor

    if not cc.compile_cache_enabled():
        return {"skipped": "XLLM_COMPILE_CACHE=0"}
    base = tempfile.mkdtemp(prefix="xllm-bench-compile-cache-")
    prev_min = os.environ.get("XLLM_COMPILE_CACHE_MIN_COMPILE_S")
    # Everything in this tiny geometry compiles fast — persist it all,
    # or the warm pass would measure nothing but re-compiles.
    os.environ["XLLM_COMPILE_CACHE_MIN_COMPILE_S"] = "0"
    try:
        cfg = EngineConfig(
            model="llama3-tiny", dtype="float32", block_size=16,
            num_blocks=32, max_running_requests=4, max_seq_len=64,
            prefill_buckets=[32], enable_mixed_step=False,
            compilation_cache_dir=base,
        )
        cold = ModelExecutor(cfg)
        cold.prewarm_programs()
        warm = ModelExecutor(cfg)
        warm.prewarm_programs()
        return {
            "programs": cold.prewarm_report["programs"],
            "compile_ms_cold": round(cold.prewarm_ms, 1),
            "compile_ms_warm": round(warm.prewarm_ms, 1),
            "cache_entries": cc.cache_entries(
                base, cold.compile_cache_key
            ),
            "cache_key": cold.compile_cache_key,
        }
    finally:
        if prev_min is None:
            os.environ.pop("XLLM_COMPILE_CACHE_MIN_COMPILE_S", None)
        else:
            os.environ["XLLM_COMPILE_CACHE_MIN_COMPILE_S"] = prev_min
        shutil.rmtree(base, ignore_errors=True)


def _run(on_tpu: bool, kv_cache_dtype: str = "auto",
         use_kernel: bool | None = None,
         weight_dtype: str = "auto",
         engine_mode: str = "both",
         attention_mode: str = "both",
         spec_mode: str = "both",
         moe_mode: str = "both",
         overlap_mode: str = "both",
         mesh=(1, 1, 1)) -> None:
    import jax

    from xllm_service_tpu.common.config import EngineConfig
    from xllm_service_tpu.ops.sampling import SamplingParams
    from xllm_service_tpu.runtime.executor import ModelExecutor, SamplingBatch

    dp, tp, ep = (int(x) for x in mesh)
    n_dev = dp * tp * ep
    # llama3-3b: largest llama member fitting v5e HBM (6.4 GB bf16 params);
    # head_dim 128 engages the Pallas decode kernel (1b's 64 cannot).
    model = "llama3-3b" if on_tpu else "llama3-tiny"
    if n_dev > 1:
        # Sharded rounds (--mesh): the 70B-class serving layout the
        # BASELINE round-3 dress rehearsal proved fits v5e at tp=8 with
        # int8 W8+KV8; the CPU virtual mesh runs the tp-shardable tiny
        # geometry (Hkv=8 divides every tp; llama3-tiny's Hkv=2 caps at
        # tp=2) so shard-aware rows exist before a chip window opens.
        # An ep axis (--mesh d,t,e with e>1) selects the MoE workload —
        # the `ep` axis is only real when experts shard over it
        # (ISSUE 15, docs/MOE.md).
        if ep > 1:
            default_model = (
                "qwen3-30b-a3b" if on_tpu else "moe-shard-tiny"
            )
        else:
            default_model = "llama3-70b" if on_tpu else "llama3-shard-tiny"
        model = os.environ.get("XLLM_BENCH_MESH_MODEL", default_model)
    R = 64 if on_tpu else 8
    prompt_len = 512 if on_tpu else 32
    decode_steps = 128 if on_tpu else 8

    cfg = EngineConfig(
        model=model,
        max_running_requests=R,
        max_seq_len=2048 if on_tpu else 256,
        # Explicit pool: the axon AOT compile path double-counts donated
        # caches, so auto-sizing to HBM headroom overcommits.
        num_blocks=512 if on_tpu else 64,
        block_size=128 if on_tpu else 16,
        # int8 KV: halves the decode attention HBM traffic (validated
        # kernel + e2e parity in tests/test_kv_quant.py).
        kv_cache_dtype=kv_cache_dtype,
        weight_dtype=weight_dtype,
        dp_size=dp, tp_size=tp, ep_size=ep,
        # Persistent jit cache: re-runs (and later rounds) skip the
        # 20-40s-per-shape TPU compiles.
        compilation_cache_dir="/tmp/xllm-jit-cache" if on_tpu else "",
    )
    prev_prefill_env = os.environ.get("XLLM_PREFILL_ATTENTION_KERNEL")
    if use_kernel is False:
        # Conservative fallback config: force BOTH Pallas paths off so a
        # kernel-compile regression can never take the bench down.
        # Restored in the finally at the end — a later attempt in this
        # process must not inherit the override.
        os.environ["XLLM_PREFILL_ATTENTION_KERNEL"] = "0"
    try:
        ex = ModelExecutor(cfg)
        # The scan harness below calls llama.decode_step inside its OWN
        # jit (not the executor's step functions), so the per-shard
        # kernel dispatch context must be declared here for the trace.
        ex._set_shard_ctx()
        bs = ex.block_size
        # The dispatch decisions the serving paths RESOLVE for this
        # cache/geometry (ops.attention.resolved_kernel_report) — the
        # record gets which kernel actually runs, not the raw env var.
        kernel_rep = (
            ex.kernel_report() if hasattr(ex, "kernel_report") else {}
        )
        rng = np.random.default_rng(0)

        # Fill every slot with a prefilled context of prompt_len tokens via the
        # BATCHED prefill path (the serving admission path) — timed, so the
        # bench also reports prefill throughput.
        from xllm_service_tpu.runtime.executor import PrefillItem

        blocks_per_seq = (prompt_len + 1 + bs - 1) // bs
        assert ex.num_blocks > R * blocks_per_seq, "KV pool too small for bench"
        tables = np.zeros((R, ex.max_blocks_per_seq), np.int32)
        next_block = 1
        items = []
        for r in range(R):
            ids = list(range(next_block, next_block + blocks_per_seq))
            next_block += blocks_per_seq
            tables[r, : len(ids)] = ids
            items.append(
                PrefillItem(
                    token_ids=rng.integers(
                        0, ex.cfg.vocab_size, (prompt_len,), np.int32
                    ),
                    start_pos=0,
                    block_table=tables[r],
                )
            )
        # Median-of-N timing (r3 lesson: the round's only CPU number was
        # 2.6x off its r2 twin, most plausibly from host load at snapshot
        # time; a single sample can't tell load from regression).
        repeats = int(os.environ.get("XLLM_BENCH_REPEATS", 3 if on_tpu else 5))
        load_before = os.getloadavg()

        ex.prefill_batch(items)  # warmup/compile (idempotent: same blocks)
        prefill_dts = []
        for _ in range(repeats):
            t0 = time.perf_counter()
            ex.prefill_batch(items)
            prefill_dts.append(time.perf_counter() - t0)
        prefill_dt = float(np.median(prefill_dts))
        prefill_tok_s = R * prompt_len / prefill_dt

        token_ids = rng.integers(0, ex.cfg.vocab_size, (R,)).astype(np.int32)
        positions = np.full((R,), prompt_len, np.int32)
        active = np.ones((R,), bool)
        s = SamplingParams(temperature=0.7)
        batch = SamplingBatch(
            np.full((R,), s.temperature, np.float32),
            np.zeros((R,), np.int32),
            np.ones((R,), np.float32),
            rng.integers(0, 2**32, (R,)).astype(np.uint32),
            np.zeros((R,), np.int32),
        )

        # Timed loop runs ON DEVICE via lax.scan (autoregressive feedback, fused
        # sampling each step) so the number measures TPU decode throughput, not
        # the dev-tunnel's per-dispatch latency. Production hosts dispatch in µs;
        # this harness round-trips through an HTTP tunnel per call.
        import jax
        import jax.numpy as jnp

        from xllm_service_tpu.models import llama
        from xllm_service_tpu.ops import sampling as sampling_ops

        mcfg = ex.cfg

        def run_steps(k_cache, v_cache, params, tokens0, pos0, tables, active,
                      temps, top_ks, top_ps, seeds):
            def body(carry, step):
                k_cache, v_cache, toks, pos = carry
                logits, k_cache, v_cache = llama.decode_step(
                    params, mcfg, k_cache, v_cache, toks, pos, tables, active,
                    use_kernel=use_kernel)
                keys = sampling_ops.make_step_keys(seeds, step)
                toks, _, _ = sampling_ops.sample_tokens(
                    logits, temps, top_ks, top_ps, keys)
                return (k_cache, v_cache, toks, pos + 1), toks

            (k_cache, v_cache, toks, _), out = jax.lax.scan(
                body, (k_cache, v_cache, tokens0, pos0),
                jnp.arange(decode_steps, dtype=jnp.int32))
            return k_cache, v_cache, out

        run = jax.jit(run_steps, donate_argnums=(0, 1))
        args = (
            jnp.asarray(token_ids), jnp.asarray(positions), jnp.asarray(tables),
            jnp.asarray(active),
            jnp.asarray(batch.temperature), jnp.asarray(batch.top_k),
            jnp.asarray(batch.top_p), jnp.asarray(batch.seeds),
        )
        # Force a host fetch of the result, not just block_until_ready: through
        # the axon dev tunnel block_until_ready can return before execution
        # completes (observed: impossible >5 PFLOP/s "timings" on v5e), and only
        # a device->host transfer reliably drains the queue.
        ex.k_cache, ex.v_cache, out = run(ex.k_cache, ex.v_cache, ex.params, *args)
        int(jnp.sum(out))  # warmup/compile + drain
        dts = []
        for _ in range(repeats):
            t0 = time.perf_counter()
            ex.k_cache, ex.v_cache, out = run(
                ex.k_cache, ex.v_cache, ex.params, *args
            )
            int(jnp.sum(out))
            dts.append(time.perf_counter() - t0)
        dt = float(np.median(dts))

        tok_per_s = R * decode_steps / dt
        baseline = R * (1000.0 / 50.0)  # reference SLO: 50 ms TPOT per request

        # Roofline context: decode FLOPs/token ≈ 2·params (matmuls) plus
        # attention score/value FLOPs over the live context.
        n_params = sum(int(np.prod(p.shape)) for p in jax.tree.leaves(ex.params))
        ctx = prompt_len + decode_steps // 2
        attn_flops = 4 * mcfg.num_layers * mcfg.num_heads * mcfg.head_dim * ctx
        flops_per_tok = 2 * n_params + attn_flops
        achieved_flops = flops_per_tok * tok_per_s
        peak = _peak_flops(jax.devices()[0])
        # Prefill MFU: matmul FLOPs + causal attention (~L^2/2 per sequence).
        # Unembed runs ONCE per sequence (last token only) and the embedding
        # is a gather, so the per-token cost excludes lm_head — unlike decode,
        # which unembeds every token.
        lm_head_params = (
            0 if mcfg.tie_word_embeddings else mcfg.hidden_size * mcfg.vocab_size
        )
        body_params = n_params - lm_head_params - mcfg.vocab_size * mcfg.hidden_size
        prefill_flops = R * (
            prompt_len * 2 * body_params
            + 2 * mcfg.hidden_size * mcfg.vocab_size  # one unembed per seq
            + 4 * mcfg.num_layers * mcfg.num_heads * mcfg.head_dim
            * prompt_len * prompt_len // 2
        )
        prefill_mfu = (
            round(prefill_flops / prefill_dt / peak, 4) if peak else None
        )

        # Analytic roofline (VERDICT r4 #2): expected MFU / HBM-GB/s per
        # config, computable on ANY backend — on CPU the expectation is
        # referenced against the bench's TPU target (v5e) so a
        # tunnel-down round still records where perf SHOULD land.
        # Weight bytes come from the LIVE param leaves (so W8/W4
        # quantized residency is counted as served); KV bytes from the
        # cache dtype. XLA's compiled-module cost_analysis is recorded
        # alongside for reference but NOT used for the expectation: it
        # counts lax.scan bodies once (verified: 17 GFLOP reported vs
        # 282 analytic on the 80-layer 70B decode), so it under-counts
        # scanned stacks ~num_layers-fold.
        peak_bw = _peak_hbm_bw(jax.devices()[0])
        roofline_ref = None
        peak_ref, bw_ref = peak, peak_bw
        if peak_ref is None or bw_ref is None:
            peak_ref, bw_ref, roofline_ref = 197e12, 819e9, "v5e"
        weight_bytes = sum(
            int(p.nbytes) for p in jax.tree.leaves(ex.params)
        )
        if cfg.kv_cache_dtype == "int8":
            kv_elem_bytes = 1
        else:
            kv_elem_bytes = 4 if cfg.dtype == "float32" else 2
        kv_row = mcfg.num_layers * mcfg.num_kv_heads * mcfg.head_dim
        # Decode step: whole weight set streams once per step (R
        # amortizes it), each slot reads its live context's K/V rows.
        # Sharded meshes: the roofline is PER DEVICE — params/FLOPs split
        # over tp*ep (dp replicates the weights), the KV stream over tp
        # (head-sharded pools) — ignoring collectives, i.e. the ideal
        # the engine_mesh_guard measures shortfall against.
        wshard = max(tp * ep, 1)
        dec_flops = R * flops_per_tok / wshard
        dec_bytes = (
            weight_bytes / wshard
            + R * ctx * kv_row * 2 * kv_elem_bytes / max(tp, 1)
        )
        decode_rl = _roofline(dec_flops, dec_bytes, peak_ref, bw_ref)
        decode_rl["expected_tok_s"] = round(
            R / decode_rl["expected_step_s"], 1
        )
        # Prefill: same weight stream + K/V writes for R*prompt_len rows;
        # FLOPs from the causal-attention-aware count above.
        pre_bytes = (
            weight_bytes / wshard
            + R * prompt_len * kv_row * 2 * kv_elem_bytes / max(tp, 1)
        )
        prefill_rl = _roofline(
            prefill_flops / wshard, pre_bytes, peak_ref, bw_ref
        )
        prefill_rl["expected_tok_s"] = round(
            R * prompt_len / prefill_rl["expected_step_s"], 1
        )
        # Opt-in: lowering again is a SECOND full XLA compile of the
        # decode scan (the jit dispatch cache is separate from the AOT
        # path) — not worth default bench time for a reference-only
        # field.
        # Engine-level A/B: the full InferenceEngine loop in sync vs
        # overlapped stepping (CPU tiny-model only — through the TPU dev
        # tunnel each engine.step pays ~100 ms of dispatch latency, which
        # would measure the tunnel, not the pipeline).
        engine_bench = None
        attention_bench = None
        spec_bench = None
        if (
            not on_tpu
            and n_dev == 1
            and not os.environ.get("XLLM_BENCH_SKIP_ENGINE_AB")
        ):
            engine_bench = {}
            modes = (
                ("sync", "overlap") if engine_mode == "both"
                else (engine_mode,)
            )
            for m in modes:
                engine_bench[m] = _engine_bench(sync=(m == "sync"))
            # Mixed-vs-split attention A/B (--attention-mode, ISSUE 9):
            # same full-engine harness, overlapped stepping, toggling
            # ONLY the step builder (ragged mixed batch vs alternating
            # prefill/decode). "ragged" reuses the engine_bench overlap
            # row when present — identical config, no second run.
            attention_bench = {}
            amodes = (
                ("split", "ragged") if attention_mode == "both"
                else (attention_mode,)
            )
            for m in amodes:
                if m == "ragged" and "overlap" in engine_bench:
                    attention_bench[m] = engine_bench["overlap"]
                else:
                    attention_bench[m] = _engine_bench(
                        sync=False, mixed=(m == "ragged")
                    )
            # Combined-path A/B (--spec-mode, ISSUE 13): speculative
            # decoding through the composed pipeline (overlap + mixed
            # verify batch + device-resident accepted-token feedback)
            # vs the sync+split verify engine — engine_spec_guard
            # (exit 3) enforces composed >= 95% of sync+split on CPU;
            # the real win lands in the TPU window.
            spec_bench = {}
            smodes = (
                ("composed", "sync_split") if spec_mode == "both"
                else ("composed",) if spec_mode == "composed"
                else ("sync_split",)
            )
            for m in smodes:
                spec_bench[m] = _engine_bench(
                    sync=(m == "sync_split"),
                    mixed=(m == "composed"),
                    spec=3,
                )

        # MoE dispatch A/B (--moe, ISSUE 15): the grouped ragged expert
        # dispatch vs the dense all-experts einsum on moe-shard-tiny —
        # same model, same router, matched active params; only the
        # dispatch strategy differs. UNLIKE the other engine A/B
        # sections this also runs on TPU (n_dev == 1): that is the only
        # backend where the grouped row resolves to the Pallas kernel,
        # so gating it CPU-only would leave engine_moe_guard permanently
        # dead on the one backend it exists for. engine_moe_guard
        # (exit 3) arms on the resolved `grouped` dispatch and abstains
        # loudly otherwise (CPU runs the grouped-ref oracle — parity is
        # tier-1's job there — and the interpret hook measures the
        # interpreter, never the chip).
        moe_bench = None
        if (
            n_dev == 1
            and not os.environ.get("XLLM_BENCH_SKIP_ENGINE_AB")
        ):
            moe_bench = {}
            mmodes = (
                ("grouped", "dense") if moe_mode == "both"
                else (moe_mode,)
            )
            for m in mmodes:
                moe_bench[m] = _engine_bench(
                    sync=False, model="moe-shard-tiny", moe=m,
                )

        # Latency-hiding collectives A/B (--overlap, ISSUE 18): the
        # ring collective-matmul schedule vs the plain psum/einsum
        # combines, full-engine harness. On a pure-tp mesh (--mesh
        # 1,N,1 — CPU virtual devices work) the schedule actually
        # engages on the tp-sharded tiny model; on a single-device run
        # the rows still print (original einsum both sides) and
        # engine_overlap_collectives_guard abstains loudly — the
        # documented single-device abstention.
        overlap_bench = None
        if (
            not on_tpu
            and dp == 1 and ep == 1
            and not os.environ.get("XLLM_BENCH_SKIP_ENGINE_AB")
        ):
            overlap_bench = {}
            omodes = (
                ("on", "off") if overlap_mode == "both"
                else (overlap_mode,)
            )
            omodel = "llama3-shard-tiny" if tp > 1 else "llama3-tiny"
            for m in omodes:
                overlap_bench[m] = _engine_bench(
                    sync=False, model=omodel, overlap=m, tp=tp,
                )

        xla_cost = None
        if os.environ.get("XLLM_BENCH_XLA_COST"):
            try:
                xla_cost = _cost_analysis(
                    run.lower(
                        ex.k_cache, ex.v_cache, ex.params, *args
                    ).compile()
                )
            except Exception:
                xla_cost = None

        # Cold-vs-warm compile cache row (ISSUE 18): LAST section — it
        # re-points jax's persistent cache at a throwaway keyed dir
        # (deleted on exit), so nothing may compile after it in this
        # process.
        compile_cache_bench = None
        if (
            not on_tpu
            and n_dev == 1
            and not os.environ.get("XLLM_BENCH_SKIP_ENGINE_AB")
        ):
            compile_cache_bench = _compile_cache_bench()
        print(json.dumps({
            "metric": f"decode_throughput_{model}_bs{R}",
            "value": round(tok_per_s, 1),
            "unit": "tokens/s",
            "vs_baseline": round(tok_per_s / baseline, 3),
            "backend": jax.default_backend(),
            "tpot_ms": round(1000.0 * dt / decode_steps, 3),
            "mfu": round(achieved_flops / peak, 4) if peak else None,
            "prefill_tok_s": round(prefill_tok_s, 1),
            "prefill_mfu": prefill_mfu,
            "attention_kernel": (
                "gather (forced-off)" if use_kernel is False
                else kernel_rep.get("decode", "unknown")
            ),
            "prefill_kernel": (
                "blockwise (forced-off)" if use_kernel is False
                else kernel_rep.get("prefill", "unknown")
            ),
            "mixed_kernel": kernel_rep.get("mixed"),
            "mq_kernel": kernel_rep.get("mq"),
            # Shard-aware row (--mesh, docs/SHARDING.md): the mesh this
            # engine ran on and how many per-shard kernel launches one
            # attention dispatch fans into (1 = single-device or the
            # XLLM_SHARDED_KERNELS=0 GSPMD escape) — MULTICHIP/BENCH
            # rounds compare across mesh shapes on these columns.
            "mesh": {"dp": dp, "tp": tp, "ep": ep},
            "kernel_shards": kernel_rep.get("shards", 1),
            "kv_cache_dtype": cfg.kv_cache_dtype,
            "weight_dtype": cfg.weight_dtype,
            # Analytic roofline expectations ("roofline_ref" names the
            # referenced chip when the run itself is not on TPU). Decode
            # must be HBM-bound: weights + KV stream once per step.
            "expected_mfu": decode_rl["expected_mfu"],
            "expected_hbm_gbps": decode_rl["expected_hbm_gbps"],
            "decode_roofline": decode_rl,
            "prefill_roofline": prefill_rl,
            "roofline_ref": roofline_ref,
            # Raw XLA compiled-module numbers, for reference only (scan
            # bodies are counted once — see comment above).
            "xla_cost_analysis": (
                {"flops": xla_cost[0], "bytes": xla_cost[1]}
                if xla_cost else None
            ),
            # Full-engine stepping-mode A/B (llama3-tiny, R=8): decode
            # tokens/s, host_gap_ms, and overlap depth per mode — the
            # overlapped (default) engine must not lose to the sync
            # escape hatch (engine_overlap_guard enforces it).
            "engine_bench": engine_bench,
            "engine_mode": engine_mode,
            # Mixed-vs-split attention A/B (--attention-mode): one ragged
            # dispatch per iteration vs the alternating split-step escape
            # hatch — engine_ragged_guard (exit 3) enforces ragged ≥ 95%
            # of split (docs/KERNELS.md).
            "attention_bench": attention_bench,
            "attention_mode": attention_mode,
            # Combined-path A/B (--spec-mode): speculative decode on the
            # composed overlap+mixed pipeline vs sync+split verify —
            # engine_spec_guard (exit 3) enforces the floor (ISSUE 13,
            # docs/ENGINE_PIPELINE.md).
            "spec_bench": spec_bench,
            "spec_mode": spec_mode,
            # MoE dispatch A/B (--moe): grouped ragged expert dispatch
            # vs dense all-experts at matched active params —
            # engine_moe_guard (exit 3) enforces the floor when the
            # Pallas dispatch actually ran (ISSUE 15, docs/MOE.md).
            "moe_bench": moe_bench,
            "moe_mode": moe_mode,
            # Latency-hiding collectives A/B (--overlap): ring
            # collective-matmul combines vs plain psum on the
            # tp-sharded engine — engine_overlap_collectives_guard
            # (exit 3) floors the pair when the schedule actually
            # engaged and abstains loudly on a single-device mesh
            # (ISSUE 18, docs/SHARDING.md "Hiding the mesh").
            "overlap_bench": overlap_bench,
            "overlap_mode": overlap_mode,
            # Cold-vs-warm persistent compile cache prewarm (ISSUE 18):
            # compile_ms_cold pays every XLA compile, compile_ms_warm
            # reloads the keyed on-disk cache — the restarted-instance
            # path. engine_host_gap_guard rides the engine rows above.
            "compile_cache_bench": compile_cache_bench,
            # The MoE dispatch THIS bench's main model resolved (None
            # for dense models).
            "moe_kernel": kernel_rep.get("moe"),
            "moe_shards": kernel_rep.get("moe_shards"),
            # Methodology markers: median of N repeats, the per-repeat
            # spread, and the host's 1-min load average around the run —
            # a hot host shows up here instead of masquerading as a
            # regression (r3 weak #1).
            "repeats": repeats,
            "cpu_count": os.cpu_count(),
            "decode_dt_spread_ms": [round(1000 * d, 1) for d in dts],
            "loadavg_1m": round(os.getloadavg()[0], 1),
            "loadavg_1m_start": round(load_before[0], 1),
        }))
    finally:
        if use_kernel is False:
            if prev_prefill_env is None:
                os.environ.pop("XLLM_PREFILL_ATTENTION_KERNEL", None)
            else:
                os.environ["XLLM_PREFILL_ATTENTION_KERNEL"] = (
                    prev_prefill_env
                )


def _peak_flops(device) -> float | None:
    """Peak bf16 FLOP/s by device kind; None on CPU (MFU meaningless)."""
    kind = getattr(device, "device_kind", "").lower()
    table = {
        "v6": 918e12, "v5p": 459e12, "v5e": 197e12, "v5 lite": 197e12,
        "v5": 459e12, "v4": 275e12,
    }
    for key, peak in table.items():
        if key in kind:
            return peak
    return None


def _peak_hbm_bw(device) -> float | None:
    """Peak HBM bandwidth (bytes/s) by device kind; None on CPU."""
    kind = getattr(device, "device_kind", "").lower()
    table = {
        "v6": 1640e9, "v5p": 2765e9, "v5e": 819e9, "v5 lite": 819e9,
        "v5": 2765e9, "v4": 1228e9,
    }
    for key, bw in table.items():
        if key in kind:
            return bw
    return None


def _cost_analysis(compiled) -> "tuple[float, float] | None":
    """(flops, bytes_accessed) from a compiled executable's XLA cost
    analysis, or None when the backend doesn't report it."""
    try:
        cost = compiled.cost_analysis()
    except Exception:
        return None
    if isinstance(cost, (list, tuple)):
        cost = cost[0] if cost else None
    if not cost:
        return None
    flops = float(cost.get("flops", 0.0))
    bts = float(cost.get("bytes accessed", 0.0))
    if flops <= 0 or bts <= 0:
        return None
    return flops, bts


def _roofline(flops: float, bts: float, peak_flops: float,
              peak_bw: float) -> dict:
    """Analytic roofline for one compiled step: expected step time is
    max(compute time, HBM time); expected_mfu / expected_hbm_gbps are
    what the step achieves AT that bound (VERDICT r4 #2 — a perf
    expectation that exists even when no chip is reachable)."""
    t_compute = flops / peak_flops
    t_hbm = bts / peak_bw
    t = max(t_compute, t_hbm)
    return {
        "flops": flops,
        "bytes": bts,
        "expected_step_s": t,
        "expected_mfu": round(flops / (t * peak_flops), 4),
        "expected_hbm_gbps": round(bts / t / 1e9, 1),
        "bound": "hbm" if t_hbm >= t_compute else "compute",
        "arithmetic_intensity": round(flops / bts, 2),
        "ridge_intensity": round(peak_flops / peak_bw, 2),
    }


if __name__ == "__main__":
    main()
