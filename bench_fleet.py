#!/usr/bin/env python
"""Fleet-scale storm bench (ROADMAP item 2; docs/FAULT_TOLERANCE.md).

Drives the REAL master stack — actual Scheduler, routing policies,
prefix fabric, breaker, election, goodput controller, admission front
door — through `cluster/fleet_sim`'s discrete-event harness at 50+
simulated instances and 10k+ concurrent streams, in seconds of wall
time. Five storm scenarios (see cluster/fleet_sim/traces.py):

    diurnal          sinusoidal day/night swing, peak >10k concurrent
    burst            10x arrival spike mid-trace
    zipf_prefix      Zipf-skewed shared prefixes (CAR + prefix index)
    straggler        ~6% of the fleet serving 6x slow
    rolling_restart  drain -> rejoin EVERY instance while traffic flows

Each scenario carries an exit-3 guard: zero unrecovered streams,
bounded p99 sim-TTFT, a goodput floor, and (for the overload
scenarios) a peak-concurrency floor proving the harness actually
reached fleet scale. One JSON line per scenario.

Two extra modes:

    --ab        admission on/off A/B on an overload trace past the
                saturation knee: with XLLM_ADMISSION off the fleet
                accepts everything and p99 TTFT collapses past the SLO;
                with the front door on (global-inflight cap + per-tenant
                buckets) excess arrivals shed with Retry-After and the
                ADMITTED streams keep their SLO. Guard: admission holds
                >=1.3x the SLO-goodput of open-door, and sheds > 0.

    --ceiling   master-throughput ceiling: flat-out request storms at
                instance counts [10, 25, 50, 100] measuring CONTROL-
                PLANE requests/s (schedule + route + deliver through the
                real scheduler, wall time). The table is the entry
                criterion for ROADMAP item 7 (clustered meta-master):
                shard the master only when this ceiling is the
                bottleneck. Results land in BASELINE.md.

    python bench_fleet.py                      # 5 scenarios, guards on
    python bench_fleet.py --quick              # small sizes, CI-able
    python bench_fleet.py --ab
    python bench_fleet.py --ceiling
"""

from __future__ import annotations

import argparse
import json
import sys

from xllm_service_tpu.cluster.fleet_sim import FleetSim, SCENARIOS, make_trace
from xllm_service_tpu.common.config import ServiceConfig

# Scenario -> (num_requests, duration_s, and guard thresholds) at FULL
# scale (50 instances). p99 bounds are sim-time seconds under the sim's
# service model (BASE_TTFT 0.2s inflated by load); goodput floors are
# SLO-met generated tokens per sim second. Guards are deliberately loose
# ~2x margins against scheduler-policy drift, tight enough to catch a
# recovery or routing regression (which shows up as unrecovered > 0 or
# an order-of-magnitude goodput drop, not 10%).
FULL = {
    #                requests  duration  p99<=   goodput>=  peak>=
    "diurnal":        (30000,     45.0,   8.0,     8000.0,   10000),
    "burst":          (20000,     60.0,   8.0,     5000.0,    4000),
    "zipf_prefix":    ( 6000,     60.0,   6.0,     1500.0,       0),
    "straggler":      ( 6000,     60.0,  10.0,     1000.0,       0),
    "rolling_restart":( 4000,    120.0,   4.0,      800.0,       0),
}
# --quick: ~10x smaller, guards scale with it (CI smoke, <5 s total).
QUICK = {
    "diurnal":        ( 3000,     30.0,   8.0,      800.0,     400),
    "burst":          ( 2000,     30.0,   8.0,      500.0,     200),
    "zipf_prefix":    ( 1000,     30.0,   6.0,      250.0,       0),
    "straggler":      ( 1000,     30.0,  10.0,      150.0,       0),
    "rolling_restart":( 1000,     60.0,   4.0,      150.0,       0),
}


def run_scenarios(args) -> int:
    table = QUICK if args.quick else FULL
    n_inst = args.instances
    names = (
        [s.strip() for s in args.scenarios.split(",") if s.strip()]
        if args.scenarios else list(SCENARIOS)
    )
    rc = 0
    for name in names:
        reqs, dur, p99_max, goodput_min, peak_min = table[name]
        if args.requests:
            reqs = args.requests
        trace = make_trace(name, reqs, dur, n_inst, seed=args.seed)
        sim = FleetSim(
            num_instances=n_inst, seed=args.seed, policy=trace.policy,
            slo_ttft_s=args.slo_ttft_s,
        )
        try:
            rep = sim.run(trace)
        finally:
            sim.close()

        reasons = []
        if rep.unrecovered != 0:
            reasons.append(f"{rep.unrecovered} unrecovered streams")
        if rep.failed != 0:
            reasons.append(f"{rep.failed} failed streams")
        if rep.p99_ttft_s > p99_max:
            reasons.append(
                f"p99 TTFT {rep.p99_ttft_s:.2f}s > {p99_max}s"
            )
        if rep.goodput_tok_s < goodput_min:
            reasons.append(
                f"goodput {rep.goodput_tok_s:.0f} tok/s < {goodput_min:.0f}"
            )
        if rep.peak_concurrent < peak_min:
            reasons.append(
                f"peak {rep.peak_concurrent} concurrent < {peak_min}"
            )
        out = rep.to_json()
        out["metric"] = "fleet_sim"
        out["fleet_guard"] = "ok" if not reasons else "; ".join(reasons)
        print(json.dumps(out))
        if reasons:
            rc = 3
    return rc


def run_ab(args) -> int:
    """Admission on/off A/B past the saturation knee. Same overload
    trace twice; the SLO is deliberately tight (default 3s) so the
    open-door run's queueing collapse costs it SLO-goodput while the
    capped run keeps its admitted streams fast."""
    n_inst = args.instances
    reqs = args.requests or (4000 if args.quick else 30000)
    dur = 20.0 if args.quick else 45.0
    slo = args.slo_ttft_s if args.slo_ttft_s != 30.0 else 3.0

    results = {}
    for label, admission in (("off", False), ("on", True)):
        cfg = ServiceConfig()
        if admission:
            # Global cap near the fleet's service knee (instances x
            # per-instance capacity x small queue allowance); per-tenant
            # cap at half of it so one tenant cannot own the fleet.
            cfg.admission_max_global_inflight = n_inst * 40
            cfg.admission_max_inflight = n_inst * 20
            cfg.admission_queue_timeout_s = 0.0  # shed, never park
        trace = make_trace("burst", reqs, dur, n_inst, seed=args.seed)
        sim = FleetSim(
            num_instances=n_inst, seed=args.seed, policy=trace.policy,
            admission=admission, slo_ttft_s=slo, config=cfg,
        )
        try:
            rep = sim.run(trace)
        finally:
            sim.close()
        results[label] = rep

    off, on = results["off"], results["on"]
    reasons = []
    if on.unrecovered or off.unrecovered:
        reasons.append("unrecovered streams in A/B run")
    if on.shed == 0:
        reasons.append("admission-on run shed nothing (knee not reached)")
    if on.goodput_tok_s < off.goodput_tok_s * 1.3:
        reasons.append(
            f"admission goodput {on.goodput_tok_s:.0f} not >= 1.3x "
            f"open-door {off.goodput_tok_s:.0f}"
        )
    print(json.dumps({
        "metric": "fleet_admission_ab",
        "instances": n_inst,
        "requests": reqs,
        "slo_ttft_s": slo,
        "off": {
            "goodput_tok_s": round(off.goodput_tok_s, 1),
            "p99_ttft_s": round(off.p99_ttft_s, 3),
            "peak_concurrent": off.peak_concurrent,
            "shed": off.shed,
        },
        "on": {
            "goodput_tok_s": round(on.goodput_tok_s, 1),
            "p99_ttft_s": round(on.p99_ttft_s, 3),
            "peak_concurrent": on.peak_concurrent,
            "shed": on.shed,
            "sheds_by_reason": on.sheds_by_reason,
        },
        "admission_ab_guard": "ok" if not reasons else "; ".join(reasons),
    }))
    return 3 if reasons else 0


def run_ceiling(args) -> int:
    """Master control-plane throughput ceiling: a flat-out storm at each
    instance count, reporting wall-clock requests/s through the REAL
    scheduler (admission -> route -> record -> dispatch -> 2 deliveries
    -> finish). No guard — this is a measurement, the BASELINE.md entry
    criterion for sharding the master (ROADMAP item 7)."""
    reqs = args.requests or (2000 if args.quick else 10000)
    counts = [10, 25, 50, 100]
    rows = []
    for n_inst in counts:
        trace = make_trace("burst", reqs, 10.0, n_inst, seed=args.seed)
        sim = FleetSim(
            num_instances=n_inst, seed=args.seed, policy=trace.policy,
        )
        try:
            rep = sim.run(trace)
        finally:
            sim.close()
        rows.append({
            "instances": n_inst,
            "requests": rep.submitted,
            "unrecovered": rep.unrecovered,
            "wall_s": round(rep.wall_s, 2),
            "control_plane_rps": round(rep.submitted / rep.wall_s, 1),
            "events_per_s": round(rep.events / rep.wall_s, 1),
        })
        print(json.dumps({"metric": "master_ceiling", **rows[-1]}))
    print(json.dumps({"metric": "master_ceiling_table", "rows": rows}))
    return 0


def main() -> None:
    p = argparse.ArgumentParser("xllm-service-tpu fleet storm bench")
    p.add_argument("--instances", type=int, default=50)
    p.add_argument(
        "--requests", type=int, default=0,
        help="override per-scenario request count (0 = scenario default)",
    )
    p.add_argument(
        "--scenarios", default="",
        help=f"comma list from {sorted(SCENARIOS)} (default: all)",
    )
    p.add_argument("--seed", type=int, default=1)
    p.add_argument(
        "--slo-ttft-s", type=float, default=30.0,
        help="sim-time TTFT SLO for goodput accounting",
    )
    p.add_argument(
        "--quick", action="store_true",
        help="~10x smaller sizes with matching guards (CI smoke)",
    )
    p.add_argument("--ab", action="store_true",
                   help="admission on/off A/B instead of the scenarios")
    p.add_argument("--ceiling", action="store_true",
                   help="master-throughput ceiling table instead")
    args = p.parse_args()

    if args.ab:
        rc = run_ab(args)
    elif args.ceiling:
        rc = run_ceiling(args)
    else:
        rc = run_scenarios(args)
    if rc:
        sys.exit(rc)


if __name__ == "__main__":
    main()
