#!/usr/bin/env python
"""Control-plane smoke client: exercises the master's RPC surface the way
an engine instance does — hello, register, heartbeat, instance listing
(reference xllm_service/examples/rpc_client_test.cpp:44-58).

    python -m xllm_service_tpu.api.master &
    python examples/rpc_client.py --rpc-addr 127.0.0.1:9996
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from xllm_service_tpu.api.client import MasterClient  # noqa: E402
from xllm_service_tpu.api.http_utils import get_json
from xllm_service_tpu.common.types import (
    InstanceMetaInfo,
    InstanceType,
    LoadMetrics,
)


def main() -> None:
    p = argparse.ArgumentParser("xllm-service-tpu rpc smoke client")
    p.add_argument("--rpc-addr", default="127.0.0.1:9996")
    args = p.parse_args()

    client = MasterClient(args.rpc_addr)
    print("hello:", client.hello("smoke-client"))

    meta = InstanceMetaInfo(
        name="smoke-instance",
        rpc_address="127.0.0.1:0",
        http_address="127.0.0.1:0",
        model_name="llama3-tiny",
        type=InstanceType.MIX,
    )
    print("register:", client.register(meta))
    print(
        "heartbeat:",
        client.heartbeat(
            meta.name,
            load_metrics=LoadMetrics(waiting_requests_num=0,
                                     gpu_cache_usage_perc=0.0),
        ),
    )
    code, info = get_json(
        args.rpc_addr, f"/rpc/instance_info?name={meta.name}"
    )
    print("instance_info:", code, json.dumps(info)[:400])
    code, prefills = get_json(args.rpc_addr, "/rpc/static_prefill_list")
    print("static_prefill_list:", code, json.dumps(prefills)[:200])


if __name__ == "__main__":
    main()
