#!/usr/bin/env python
"""Multimodal smoke client: send a real PNG, an mp4 clip, or a WAV clip
through `/v1/chat/completions` on a running cluster with ENCODE
instances (the EPD path: encoder -> embedding injection -> prefill).

    # vision cluster (Qwen2-VL combined checkpoint on both roles)
    python -m xllm_service_tpu.api.master \
        --mm-image-processor qwen2vl --mm-image-size 448 &
    python -m xllm_service_tpu.api.instance --master-rpc-addr 127.0.0.1:9996 \
        --model q2vl --checkpoint-path /ckpt --instance-type MIX &
    python -m xllm_service_tpu.api.instance --master-rpc-addr 127.0.0.1:9996 \
        --model q2vl --checkpoint-path /ckpt --instance-type ENCODE &

    python examples/multimodal_client.py --addr 127.0.0.1:9999 \
        --model q2vl --image cat.png
    python examples/multimodal_client.py --addr 127.0.0.1:9999 \
        --model q2vl --video clip.mp4
    # audio cluster: an ENCODE instance with --model qwen2audio-encoder
    # (or an audio checkpoint) + master --mm-audio-mel-frames 3000
    python examples/multimodal_client.py --addr 127.0.0.1:9999 \
        --model qwen2-audio --audio speech.wav
"""

from __future__ import annotations

import argparse
import base64
import http.client
import json
import mimetypes
import sys


def data_url(path: str) -> tuple:
    """(part_key, data URL) for an image/video/audio file."""
    mime = mimetypes.guess_type(path)[0] or ""
    kind = mime.split("/")[0]
    if kind not in ("image", "video", "audio"):
        sys.exit(f"{path}: unsupported media type {mime!r}")
    with open(path, "rb") as f:
        payload = base64.b64encode(f.read()).decode()
    return f"{kind}_url", f"data:{mime};base64,{payload}"


def main() -> None:
    p = argparse.ArgumentParser("multimodal smoke client")
    p.add_argument("--addr", default="127.0.0.1:9999")
    p.add_argument("--model", required=True)
    p.add_argument("--prompt", default="Describe this.")
    p.add_argument("--max-tokens", type=int, default=64)
    g = p.add_mutually_exclusive_group(required=True)
    g.add_argument("--image")
    g.add_argument("--video")
    g.add_argument("--audio")
    args = p.parse_args()

    media_path = args.image or args.video or args.audio
    part_key, url = data_url(media_path)
    body = {
        "model": args.model,
        "max_tokens": args.max_tokens,
        "temperature": 0.0,
        "messages": [{
            "role": "user",
            "content": [
                {"type": "text", "text": args.prompt + " "},
                {"type": part_key, part_key: {"url": url}},
            ],
        }],
    }
    host, _, port = args.addr.partition(":")
    conn = http.client.HTTPConnection(host, int(port), timeout=600.0)
    conn.request(
        "POST", "/v1/chat/completions", body=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"},
    )
    resp = conn.getresponse()
    out = json.loads(resp.read())
    if resp.status != 200:
        sys.exit(f"HTTP {resp.status}: {json.dumps(out, indent=2)}")
    print(out["choices"][0]["message"]["content"])


if __name__ == "__main__":
    main()
