#!/usr/bin/env python
"""Smoke client for a running xllm-service-tpu master (or a direct
instance): completion, chat completion, and streaming over the OpenAI
surface. The runnable analog of the reference's manual smoke client
(reference xllm_service/examples/http_client_test.cpp:71-145).

    python -m xllm_service_tpu.api.master &          # service tier
    python -m xllm_service_tpu.api.instance \
        --master-rpc-addr 127.0.0.1:9996 &           # engine tier
    python examples/http_client.py --addr 127.0.0.1:9999
"""

from __future__ import annotations

import argparse
import http.client
import json


def _connect(addr: str, path: str, body: dict):
    host, _, port = addr.partition(":")
    conn = http.client.HTTPConnection(host, int(port), timeout=300.0)
    conn.request(
        "POST", path, body=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"},
    )
    return conn, conn.getresponse()


def post(addr: str, path: str, body: dict):
    conn, resp = _connect(addr, path, body)
    data = resp.read()
    conn.close()
    return resp.status, json.loads(data) if data else {}


def post_stream(addr: str, path: str, body: dict):
    conn, resp = _connect(addr, path, body)
    assert resp.status == 200, resp.read()
    for raw in resp:
        line = raw.decode().strip()
        if line.startswith("data: "):
            payload = line[len("data: "):]
            if payload == "[DONE]":
                break
            yield json.loads(payload)
    conn.close()


def main() -> None:
    p = argparse.ArgumentParser("xllm-service-tpu smoke client")
    p.add_argument("--addr", default="127.0.0.1:9999")
    p.add_argument("--model", default="llama3-tiny")
    p.add_argument("--prompt", default="hello, tpu serving")
    p.add_argument("--max-tokens", type=int, default=16)
    args = p.parse_args()

    print("== /v1/completions ==")
    code, body = post(
        args.addr, "/v1/completions",
        {"model": args.model, "prompt": args.prompt,
         "max_tokens": args.max_tokens, "temperature": 0.0},
    )
    print(code, json.dumps(body, indent=2)[:400])

    print("== /v1/chat/completions ==")
    code, body = post(
        args.addr, "/v1/chat/completions",
        {"model": args.model,
         "messages": [{"role": "user", "content": args.prompt}],
         "max_tokens": args.max_tokens, "temperature": 0.0},
    )
    print(code, json.dumps(body, indent=2)[:400])

    print("== streaming ==")
    text = []
    for event in post_stream(
        args.addr, "/v1/completions",
        {"model": args.model, "prompt": args.prompt,
         "max_tokens": args.max_tokens, "temperature": 0.0, "stream": True},
    ):
        for c in event.get("choices", []):
            text.append(c.get("text", ""))
    print("streamed:", "".join(text))


if __name__ == "__main__":
    main()
